"""Continuous-batching LLM inference engine + serve deployment.

TPU-native serving path (SURVEY.md §7 step 9: "continuous batching
replica — KV cache in HBM, prefill/decode split — for the Llama-8B
serving target"; the reference delegates this entirely to vLLM,
doc/source/serve/doc_code/vllm_example.py).

Engine design around XLA's static shapes:
- a fixed pool of `num_slots` sequence slots backed by one static KV
  cache (models/generate.py); admission = prefill into a free slot,
  one bucketed-compile per prompt-length bucket;
- every engine tick runs ONE compiled decode step for ALL slots (the
  continuous-batching property: sequences join/leave between ticks,
  the compiled program never changes shape);
- per-slot temperature rides a (B,) operand, so mixed sampling configs
  share the tick; finished slots are ignored until readmission.

TTFT = submit→first-token (prefill-bound); per-request metrics are
recorded for the serving benchmark (BASELINE.md north-star: req/s +
p50 TTFT).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.graphable import graphable
from ..models.generate import (
    KVCache,
    compute_prefix_kv,
    decode_multi,
    decode_multi_lp,
    decode_step,
    first_token_sample,
    first_token_sample_lp,
    first_token_suffix_sample,
    first_token_suffix_sample_lp,
    init_kv_cache,
    prefill_sample_batch,
    prefill_sample_batch_lp,
    prefill_suffix_batch,
    prefill_suffix_batch_lp,
)
from ..models.transformer import TransformerConfig, init_params


def default_buckets(max_prompt_len: int) -> List[int]:
    out, b = [], 16
    while b < max_prompt_len:
        out.append(b)
        b *= 2
    out.append(max_prompt_len)
    return out


@partial(jax.jit, static_argnums=(3,))
def _sample_batch(logits: jax.Array, temps: jax.Array, key: jax.Array,
                  top_k: int) -> jax.Array:
    """(B,V) logits -> (B,) tokens; temp<=0 slots decode greedily."""
    from ..models.generate import sample

    return sample(logits, key, temperature=temps, top_k=top_k)


@partial(jax.jit, static_argnums=(3,))
def _sample_batch_lp(logits: jax.Array, temps: jax.Array, key: jax.Array,
                     top_k: int):
    """(B,V) logits -> ((B,) tokens, (B,) log-probs of those tokens)."""
    from ..models.generate import sample, token_logp

    toks = sample(logits, key, temperature=temps, top_k=top_k)
    return toks, token_logp(logits, toks)


@dataclass
class GenRequest:
    prompt: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    eos_token: Optional[int] = None
    # filled by the engine
    id: int = 0
    submit_ts: float = 0.0
    # First time engine compute touched the request (slot admission or
    # the queue-side early-first-token pass): splits TTFT into
    # queue_s (submit→admit) vs prefill_s (admit→first token) for the
    # critpath/TTFT waterfall.
    admit_ts: float = 0.0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    stream: "queue.Queue" = field(default_factory=queue.Queue)
    tokens: List[int] = field(default_factory=list)
    # log π(tok) per emitted token (raw-logits log_softmax), filled only
    # on engines built with capture_logprobs=True; index-aligned with
    # `tokens`.
    logprobs: List[float] = field(default_factory=list)
    error: Optional[str] = None
    # Set once the terminal None has been consumed (engine-internal).
    _done: bool = field(default=False, repr=False)
    # First token served queue-side before any slot freed (engine-
    # internal; _admit resumes decode from it).
    _early_tok: Optional[int] = field(default=None, repr=False)

    @property
    def ttft_s(self) -> float:
        return self.first_token_ts - self.submit_ts

    @property
    def latency_s(self) -> float:
        return self.finish_ts - self.submit_ts

    @property
    def queue_s(self) -> float:
        """Admission-queue wait (0.0 until admitted)."""
        if self.admit_ts == 0.0:
            return 0.0
        return self.admit_ts - self.submit_ts

    @property
    def prefill_s(self) -> float:
        """Admission → first token (0.0 until the first token)."""
        if self.admit_ts == 0.0 or self.first_token_ts == 0.0:
            return 0.0
        return self.first_token_ts - self.admit_ts

    @property
    def decode_s(self) -> float:
        """First token → finish (0.0 until finished)."""
        if self.first_token_ts == 0.0 or self.finish_ts == 0.0:
            return 0.0
        return self.finish_ts - self.first_token_ts

    def __iter__(self) -> Iterator[int]:
        if self._done:
            # Replay: the stream was already drained (by result() or a
            # prior iteration) — blocking on it again would hang.
            if self.error is not None:
                raise RuntimeError(f"generation failed: {self.error}")
            yield from list(self.tokens)
            return
        while True:
            tok = self.stream.get()
            if tok is None:
                self._done = True  # result() must not block after this
                if self.error is not None:
                    raise RuntimeError(f"generation failed: {self.error}")
                return
            yield tok

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """ALL generated tokens, regardless of how many were already
        consumed via streaming — idempotent and safe after __iter__."""
        if self._done:
            if self.error is not None:
                raise RuntimeError(f"generation failed: {self.error}")
            return list(self.tokens)
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            left = (max(0.0, deadline - time.monotonic())
                    if deadline is not None else None)
            tok = self.stream.get(timeout=left)
            if tok is None:
                self._done = True
                if self.error is not None:
                    raise RuntimeError(
                        f"generation failed: {self.error}")
                # self.tokens has every emitted token (the engine
                # appends there before the stream put).
                return list(self.tokens)


class _Slot:
    __slots__ = ("req", "emitted", "length", "inflight")

    def __init__(self, req: GenRequest, prompt_len: int):
        self.req = req
        self.emitted = 0
        self.length = prompt_len  # tokens in cache (grows per tick)
        # Decode ticks dispatched to the device but not yet processed
        # on the host (the pipelined block in flight). The device-side
        # cache position for this slot is length + inflight.
        self.inflight = 0


class LLMEngine:
    """Host-side continuous-batching loop over the compiled
    prefill/decode steps. Thread-safe submit; `step()` is driven either
    by `run_forever()` (background thread) or manually (tests)."""

    def __init__(self, cfg: TransformerConfig, params: Any, *,
                 num_slots: int = 4, max_seq_len: Optional[int] = None,
                 top_k: int = 0, seed: int = 0, decode_block: int = 64,
                 auto_prefix_min_hits: int = 0,
                 auto_prefix_lens: Sequence[int] = (64, 128, 256, 512),
                 mesh: Optional["jax.sharding.Mesh"] = None,
                 capture_logprobs: bool = False):
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        self.top_k = top_k
        # Per-token logp capture (RLHF rollout plane): every dispatch
        # goes through the *_lp variants, which also return
        # log_softmax(raw logits)[sampled token]; GenRequest.logprobs
        # fills index-aligned with tokens. Off by default — plain
        # serving skips the extra gather and the (k, B) f32 transfer.
        self.capture_logprobs = bool(capture_logprobs)
        # Multi-chip serving (VERDICT r4 #3): with a mesh, weights are
        # laid out by their logical axes (megatron TP via "heads"/"mlp"/
        # "vocab"→tp, ZeRO-style "embed"→fsdp) and the KV cache shards
        # across kv-heads; every compiled prefill/decode step then runs
        # SPMD with XLA-inserted collectives over ICI. An 8B model that
        # cannot fit one 16 GiB chip serves on tp=4/fsdp=2. The
        # reference reaches multi-GPU serving only through vLLM TP
        # (doc/source/serve/doc_code/vllm_example.py).
        self.mesh = mesh
        if mesh is not None:
            from ..models.transformer import param_logical_axes
            from ..parallel.sharding import shard_pytree

            with jax.sharding.set_mesh(mesh):
                params = shard_pytree(params, param_logical_axes(cfg),
                                      mesh)
        self.params = params
        # UPPER BOUND on ticks fused per dispatch (decode_multi); the
        # actual block size adapts ONLINE each step to the minimum
        # remaining generation budget among active slots, so a block
        # ends exactly when the first slot completes and its
        # replacement is admitted (no workload-tuned constant — the cap
        # only bounds the compile cache and worst-case admission
        # latency). Bigger fused blocks amortize the host↔device round
        # trip (~150 ms on a tunneled chip).
        self.decode_block = max(1, decode_block)
        with self._mesh_ctx():
            self.cache: KVCache = init_kv_cache(cfg, num_slots,
                                                self.max_seq_len)
            self.cur_tokens = jnp.zeros((num_slots,), jnp.int32)
            # Device-resident per-slot temperatures: updated by scatter
            # at admission, never re-uploaded per tick.
            self._temps = jnp.zeros((num_slots,), jnp.float32)
            self._key = jax.random.key(seed)
        self.slots: List[Optional[_Slot]] = [None] * num_slots
        # One decode block pipelined: dispatched last tick, its tokens
        # fetched/emitted next tick (overlaps the round trip with the
        # next block's compute).
        self._pending = None
        self.waiting: deque = deque()
        self.lock = threading.Lock()
        self._work = threading.Event()
        self._stop = False
        self._next_id = 0
        self.buckets = default_buckets(self.max_seq_len)
        # Registered prompt prefixes (system prompts): token-tuple ->
        # {"k","v"} device KV computed once; admission copies it into
        # the slot and prefills only the suffix (vLLM-style prefix
        # caching scoped to explicit registration — the KV cache here
        # is slot-contiguous, not paged).
        from collections import OrderedDict

        self._prefixes: "OrderedDict[tuple, Dict[str, Any]]" = \
            OrderedDict()
        self.max_cached_prefixes = 8
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        # Automatic capture (vLLM's "automatic" in automatic prefix
        # caching, at registered-prefix granularity): count block-length
        # prompt prefixes at submit; one that repeats
        # auto_prefix_min_hits times registers itself on the next engine
        # tick (registration prefills once — done engine-side so no
        # client submit blocks on it). 0 = off.
        self.auto_prefix_min_hits = int(auto_prefix_min_hits)
        self.auto_prefix_lens = tuple(sorted(auto_prefix_lens))
        self._auto_counts: "OrderedDict[tuple, int]" = OrderedDict()
        self._auto_pending: deque = deque()
        self._auto_inflight: set = set()
        self.prefix_register_failures = 0
        # aggregate stats
        self.decode_ticks = 0
        self.tokens_out = 0
        self.finished: List[Dict[str, float]] = []
        # Recent-TTFT EWMA: the router's SLO-aware tiebreak signal
        # (cheap to read every stats poll, unlike the sorted
        # percentiles in stats()).
        self._ttft_ewma: Optional[float] = None

    def _mesh_ctx(self):
        """Ambient-mesh context for every device dispatch: the in-jit
        logical-axis constraints (models/generate.py wsc calls) resolve
        against it, turning the same compiled steps into SPMD programs.
        No-op (and zero-cost) for single-chip engines."""
        if self.mesh is None:
            import contextlib
            return contextlib.nullcontext()
        return jax.sharding.set_mesh(self.mesh)

    # -- submission ---------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 64,
               temperature: float = 0.0,
               eos_token: Optional[int] = None) -> GenRequest:
        if self._stop:
            raise RuntimeError("engine is stopped")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) >= self.max_seq_len:
            raise ValueError(
                f"prompt len {len(prompt)} >= max_seq_len {self.max_seq_len}")
        req = GenRequest(prompt=list(prompt), max_new_tokens=max_new_tokens,
                         temperature=temperature, eos_token=eos_token)
        with self.lock:
            req.id = self._next_id
            self._next_id += 1
        req.submit_ts = time.monotonic()
        if self.auto_prefix_min_hits > 0:
            self._note_prefix_candidates(prompt)
        with self.lock:
            self.waiting.append(req)
        self._work.set()
        return req

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 return_logprobs: bool = False,
                 timeout: Optional[float] = None) -> Dict[str, Any]:
        """Synchronous generation: submit + wait for completion.

        With `return_logprobs=True` (requires an engine built with
        capture_logprobs=True) the result carries per-token
        log-probabilities of the sampled tokens — log_softmax of the
        RAW logits, index-aligned with `tokens` — which is what the
        RLHF rollout plane feeds the GRPO ratio term (previously GRPO
        re-ran a full forward to recompute them).

        If no background loop is running (`start()` not called), the
        engine is driven from this thread — deterministic single-thread
        mode for tests and rollout actors that own their engine."""
        if return_logprobs and not self.capture_logprobs:
            raise ValueError(
                "return_logprobs=True requires "
                "LLMEngine(..., capture_logprobs=True) — the engine "
                "only records per-token logps when built to")
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          temperature=temperature, eos_token=eos_token)
        loop = getattr(self, "_loop_thread", None)
        if loop is None or not loop.is_alive():
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while req.finish_ts == 0.0:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("generate timed out")
                self.step()
        tokens = req.result(timeout=timeout)
        out: Dict[str, Any] = {"tokens": tokens, "ttft_s": req.ttft_s,
                               "latency_s": req.latency_s,
                               "queue_s": req.queue_s,
                               "prefill_s": req.prefill_s,
                               "decode_s": req.decode_s}
        if return_logprobs:
            out["logprobs"] = list(req.logprobs)
        return out

    def _note_prefix_candidates(self, prompt: Sequence[int]) -> None:
        """Count every applicable block-length prefix BEYOND what a
        registered prefix already covers. Counting only the longest
        length would miss the feature's main target — a hot short
        system prompt followed by divergent user content (all
        longest-length keys distinct, none ever hot); counting covered
        lengths would re-register what longest-match already serves.
        Hot keys enqueue for engine-side registration; the drain's
        longest-first + covered-skip keeps nested keys of identical
        prompts from each costing a registration. Bounded table
        (LRU, 512)."""
        tokens = [int(t) for t in prompt]
        with self.lock:
            covered = 0
            for reg in self._prefixes:
                if (len(reg) > covered and len(reg) < len(tokens)
                        and tokens[:len(reg)] == list(reg)):
                    covered = len(reg)
            for L in self.auto_prefix_lens:
                if L >= len(tokens) or L >= self.max_seq_len - 1:
                    break
                if L <= covered:
                    continue
                key = tuple(tokens[:L])
                if key in self._prefixes or key in self._auto_inflight:
                    continue
                n = self._auto_counts.get(key, 0) + 1
                self._auto_counts[key] = n
                self._auto_counts.move_to_end(key)
                if n >= self.auto_prefix_min_hits:
                    del self._auto_counts[key]
                    self._auto_inflight.add(key)
                    self._auto_pending.append(key)
            while len(self._auto_counts) > 512:
                self._auto_counts.popitem(last=False)

    def _drain_auto_registrations(self) -> bool:
        """Register ONE pending hot prefix per tick (each registration
        is a prefill-sized dispatch; spreading them keeps admission
        latency bounded). Longest pending first; a pending key that is
        a PREFIX of an already-registered one is dropped — its prompts
        are almost always served by the longer registration, and if
        genuinely divergent traffic reappears it simply re-accumulates."""
        with self.lock:
            while self._auto_pending:
                key = max(self._auto_pending, key=len)
                self._auto_pending.remove(key)
                if any(len(reg) >= len(key)
                       and reg[:len(key)] == key
                       for reg in self._prefixes):
                    self._auto_inflight.discard(key)
                    continue
                break
            else:
                return False
        try:
            self.register_prefix(key)
        except ValueError:
            # The documented race: prompt family no longer fits (e.g.
            # max_seq_len shrunk relative to the candidate length).
            pass
        except Exception:  # noqa: BLE001 — device/XLA failure
            # Dropped, counted, and logged: a silently-vanishing hot
            # prefix would read as "caching stopped working".
            self.prefix_register_failures += 1
            import logging

            logging.getLogger("ray_tpu.serve").warning(
                "auto prefix registration failed (len %d); dropping",
                len(key), exc_info=True)
        finally:
            with self.lock:
                self._auto_inflight.discard(key)
        return True

    def set_params(self, params: Any) -> None:
        """Swap in a new policy (RLHF weight refresh). Device-puts
        (mesh-sharded when serving multi-chip), then recomputes every
        registered prefix — their pinned KV was built under the OLD
        weights, and serving it onward would silently mix policies in
        the captured logps."""
        if self.mesh is not None:
            from ..models.transformer import param_logical_axes
            from ..parallel.sharding import shard_pytree

            with jax.sharding.set_mesh(self.mesh):
                params = shard_pytree(
                    params, param_logical_axes(self.cfg), self.mesh)
        else:
            params = jax.device_put(params)
        with self.lock:
            self.params = params
            keys = list(self._prefixes)
            self._prefixes.clear()
        for key in keys:
            self.register_prefix(key)

    def register_prefix(self, tokens: Sequence[int]) -> None:
        """Precompute + pin the KV of a shared prompt prefix (system
        prompt). Later prompts starting with it skip its prefill
        entirely. LRU-capped at max_cached_prefixes."""
        key = tuple(int(t) for t in tokens)
        if not key:
            raise ValueError("empty prefix")
        if len(key) >= self.max_seq_len - 1:
            raise ValueError(
                f"prefix len {len(key)} leaves no room for a suffix "
                f"(max_seq_len {self.max_seq_len})")
        with self.lock:
            if key in self._prefixes:
                self._prefixes.move_to_end(key)
                return
        with self._mesh_ctx():
            pk, pv = compute_prefix_kv(self.cfg, self.params, key)
        with self.lock:
            self._prefixes[key] = {"k": pk, "v": pv}
            while len(self._prefixes) > self.max_cached_prefixes:
                self._prefixes.popitem(last=False)

    def _match_prefix(self, prompt: List[int]):
        """Longest registered prefix that strictly prefixes `prompt`
        (>=1 suffix token must remain) and whose install still fits the
        cache after suffix-bucket rounding. Returns the key or None."""
        if not self._prefixes:
            return None
        with self.lock:
            cands = sorted(self._prefixes, key=len, reverse=True)
        for key in cands:
            sp = len(key)
            if len(prompt) <= sp or tuple(prompt[:sp]) != key:
                continue
            if sp + self._bucket_for(len(prompt) - sp) > self.max_seq_len:
                continue
            with self.lock:
                entry = self._prefixes.get(key)
                if entry is not None:
                    self._prefixes.move_to_end(key)
                    # Entry captured under the lock: a concurrent
                    # register_prefix may LRU-evict the key before
                    # dispatch; the captured arrays stay valid.
                    return key, entry
        return None

    def _group_by_route(self, items: List, prompt_of):
        """Shared admission/early-token routing: split items into
        full-prefill tiles and prefix-suffix tiles (fixed W rows each).
        Returns (full [(bucket, chunk)], suffix [(pkey, entry, bucket,
        chunk)]) — ONE implementation so the two call sites can never
        route the same prompt differently."""
        by_bucket: Dict[int, List] = {}
        by_prefix: Dict[tuple, List] = {}
        entries: Dict[tuple, Dict[str, Any]] = {}
        for it in items:
            prompt = prompt_of(it)
            match = self._match_prefix(prompt)
            if match is not None:
                pkey, entry = match
                entries[pkey] = entry
                by_prefix.setdefault(pkey, []).append(it)
            else:
                by_bucket.setdefault(
                    self._bucket_for(len(prompt)), []).append(it)
        W = self._ADMIT_TILE
        full = [(b, p[off:off + W])
                for b, p in sorted(by_bucket.items())
                for off in range(0, len(p), W)]
        suffix = []
        for pkey, its in by_prefix.items():
            sub: Dict[int, List] = {}
            for it in its:
                sub.setdefault(
                    self._bucket_for(len(prompt_of(it)) - len(pkey)),
                    []).append(it)
            for b, p in sorted(sub.items()):
                for off in range(0, len(p), W):
                    suffix.append((pkey, entries[pkey], b,
                                   p[off:off + W]))
        return full, suffix

    # -- engine internals ---------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _emit(self, slot: _Slot, tok: int,
              lp: Optional[float] = None) -> None:
        slot.req.tokens.append(tok)
        if lp is not None:
            slot.req.logprobs.append(float(lp))
        slot.req.stream.put(tok)
        slot.emitted += 1
        slot.length += 1
        self.tokens_out += 1

    def _complete(self, req: GenRequest, new_tokens: int) -> None:
        """Single place for request-completion bookkeeping (slot-path
        and queue-side finishes alike)."""
        req.finish_ts = time.monotonic()
        req.stream.put(None)
        self.finished.append({
            "id": req.id,
            "ttft_s": req.ttft_s,
            "latency_s": req.latency_s,
            # TTFT waterfall: queue wait vs prefill vs decode (the
            # serve row bench.py --critpath records).
            "queue_s": req.queue_s,
            "prefill_s": req.prefill_s,
            "decode_s": req.decode_s,
            "new_tokens": new_tokens,
        })
        self._ttft_ewma = (
            req.ttft_s if self._ttft_ewma is None
            else 0.8 * self._ttft_ewma + 0.2 * req.ttft_s)

    def _finish(self, idx: int) -> None:
        slot = self.slots[idx]
        self._complete(slot.req, slot.emitted)
        self.slots[idx] = None

    _ADMIT_TILE = 8  # fixed batch tile: ONE compile per bucket, ever

    @classmethod
    def _build_tile(cls, bucket: int, rows: Sequence):
        """Pad up to _ADMIT_TILE token lists into one (W, bucket) host
        tile (+ lengths and temps). rows: [(tokens, temperature)].
        Padding on the HOST: an eager .at[].set() per prompt would
        compile a scatter kernel per distinct length (seconds each);
        numpy + one transfer doesn't."""
        W = cls._ADMIT_TILE
        buf = np.zeros((W, bucket), np.int32)
        lens = np.ones((W,), np.int32)
        temps = np.zeros((W,), np.float32)
        for j, (tokens, temp) in enumerate(rows):
            pl = len(tokens)
            buf[j, :pl] = np.asarray(tokens, np.int32)
            lens[j] = pl
            temps[j] = temp
        return buf, lens, temps

    def _admit(self) -> List:
        """Prefill waiting requests into free slots (arrival order).

        Admissions are BATCHED per prompt-length bucket into fixed
        W-row tiles and dispatched through prefill_sample_batch — a
        single-sequence prefill streams the full weights from HBM, so
        per-slot serial prefills made admission waves cost ~W× more
        device time than one batched call. All dispatches are async;
        first tokens are fetched later by _deliver_first_tokens with
        one fused host sync. Requests whose first token was already
        served by _early_first_tokens() are prefilled in the same
        batch (their sampled token is discarded and decode continues
        from the token the client saw). Returns [(idx, tok_dev)].
        """
        with self.lock:
            free = [i for i, s in enumerate(self.slots) if s is None]
            take: List = []
            while free[len(take):] and self.waiting:
                take.append(self.waiting.popleft())
        if not take:
            return []
        now = time.monotonic()
        for req in take:
            if req.admit_ts == 0.0:
                req.admit_ts = now

        admitted: List = []  # (idx, tok_dev, lp_dev|None) — pending
        # Route: prompts strictly extending a registered prefix go
        # through the suffix path (prefix KV copied, only the suffix
        # prefilled); the rest through the full path.
        W = self._ADMIT_TILE
        full, suffix = self._group_by_route(
            list(zip(take, free)), lambda it: it[0].prompt)
        chunks: List = [("full", bucket, None, chunk)
                        for bucket, chunk in full]
        chunks += [("suffix", (pkey, bucket), entry, chunk)
                   for pkey, entry, bucket, chunk in suffix]

        for ci, (kind, binfo, entry, chunk) in enumerate(chunks):
            # Padding rows scatter out of bounds (slot==num_slots) and
            # are dropped on device.
            slot_idx = np.full((W,), self.num_slots, np.int32)
            for j, (_, idx) in enumerate(chunk):
                slot_idx[j] = idx
            self._key, sub = jax.random.split(self._key)
            lps = None
            try:
                if kind == "full":
                    bucket = binfo
                    buf, lens, temps = self._build_tile(
                        bucket,
                        [(req.prompt, req.temperature)
                         for req, _ in chunk])
                    args = (self.cfg, self.params, self.cache,
                            jnp.asarray(buf), jnp.asarray(lens),
                            jnp.asarray(slot_idx), self.top_k,
                            jnp.asarray(temps), sub)
                    if self.capture_logprobs:
                        self.cache, toks, lps = \
                            prefill_sample_batch_lp(*args)
                    else:
                        self.cache, toks = prefill_sample_batch(*args)
                else:
                    pkey, bucket = binfo
                    sp = len(pkey)
                    buf, lens, temps = self._build_tile(
                        bucket,
                        [(req.prompt[sp:], req.temperature)
                         for req, _ in chunk])
                    args = (self.cfg, self.params, self.cache,
                            entry["k"], entry["v"],
                            jnp.asarray(buf), jnp.asarray(lens),
                            jnp.asarray(slot_idx), self.top_k,
                            jnp.asarray(temps), sub)
                    if self.capture_logprobs:
                        self.cache, toks, lps = \
                            prefill_suffix_batch_lp(*args)
                    else:
                        self.cache, toks = prefill_suffix_batch(*args)
                    self.prefix_hits += len(chunk)
                    self.prefix_tokens_saved += sp * len(chunk)
            except Exception:
                # put this and every unprocessed request back so
                # _fail_all can notify their clients
                with self.lock:
                    for _, _, _, later in reversed(chunks[ci:]):
                        for req, _ in reversed(later):
                            self.waiting.appendleft(req)
                raise
            self._temps = self._temps.at[slot_idx].set(
                jnp.asarray(temps), mode="drop")
            self.cur_tokens = self.cur_tokens.at[slot_idx].set(
                toks, mode="drop")
            for j, (req, idx) in enumerate(chunk):
                slot = _Slot(req, len(req.prompt))
                self.slots[idx] = slot
                early_tok = getattr(req, "_early_tok", None)
                if early_tok is not None:
                    # First token already delivered queue-side: decode
                    # continues from the token the client saw, not this
                    # batch's sample.
                    slot.emitted = len(req.tokens)
                    slot.length = len(req.prompt) + slot.emitted
                    self.cur_tokens = self.cur_tokens.at[idx].set(
                        int(early_tok))
                else:
                    admitted.append(
                        (idx, toks[j],
                         lps[j] if lps is not None else None))
        return admitted

    def _early_first_tokens(self) -> List:
        """TTFT decoupled from slot availability: queued requests that
        could not be admitted get their FIRST token from a cache-free
        batched forward (models/generate.first_token_sample), in
        arrival order, one dispatch per prompt-bucket tile. When a slot
        frees, _admit prefills the prompt and decode resumes from this
        token — the client's stream stays consistent. Returns
        [(chunk_requests, toks_dev)]; fetched by
        _deliver_first_tokens."""
        with self.lock:
            todo = [r for r in self.waiting
                    if r.first_token_ts == 0.0]
        if not todo:
            return []
        now = time.monotonic()
        for r in todo:
            if r.admit_ts == 0.0:
                # Queue-side compute IS this request's admission for
                # TTFT-waterfall purposes (prefill starts here).
                r.admit_ts = now
        outs = []
        full, suffix = self._group_by_route(todo, lambda r: r.prompt)
        for bucket, chunk in full:
            buf, lens, temps = self._build_tile(
                bucket, [(r.prompt, r.temperature) for r in chunk])
            self._key, sub = jax.random.split(self._key)
            args = (self.cfg, self.params, jnp.asarray(buf),
                    jnp.asarray(lens), jnp.asarray(temps), self.top_k,
                    sub)
            if self.capture_logprobs:
                toks, lps = first_token_sample_lp(*args)
            else:
                toks, lps = first_token_sample(*args), None
            outs.append((chunk, toks, lps))
        # Prefix-matched queued requests: suffix-only forward against
        # the stored prefix KV (same FLOP saving as slot admission).
        for pkey, entry, bucket, chunk in suffix:
            sp = len(pkey)
            buf, lens, temps = self._build_tile(
                bucket, [(r.prompt[sp:], r.temperature)
                         for r in chunk])
            self._key, sub = jax.random.split(self._key)
            args = (self.cfg, self.params, entry["k"], entry["v"],
                    jnp.asarray(buf), jnp.asarray(lens),
                    jnp.asarray(temps), self.top_k, sub)
            if self.capture_logprobs:
                toks, lps = first_token_suffix_sample_lp(*args)
            else:
                toks, lps = first_token_suffix_sample(*args), None
            self.prefix_hits += len(chunk)
            self.prefix_tokens_saved += sp * len(chunk)
            outs.append((chunk, toks, lps))
        return outs

    def _fuse_first_tokens(self, admitted: List, outs: List):
        """Concatenate every pending first token into ONE device array
        and start its host copy — enqueued BEFORE the decode block so
        the device serves it first (device execution is in-order; a
        fetch enqueued after the block would wait out the whole
        block)."""
        if not admitted and not outs:
            return None
        parts = []
        if admitted:
            parts.append(jnp.stack([t for _, t, _ in admitted]))
        parts += [t for _, t, _ in outs]
        fused = jnp.concatenate(parts)
        fused_lp = None
        if self.capture_logprobs:
            lp_parts = []
            if admitted:
                lp_parts.append(jnp.stack([l for _, _, l in admitted]))
            lp_parts += [l for _, _, l in outs]
            fused_lp = jnp.concatenate(lp_parts)
        for arr in (fused, fused_lp):
            if arr is None:
                continue
            try:
                arr.copy_to_host_async()
            except Exception:  # noqa: BLE001 — no async copy
                pass
        return fused, fused_lp

    def _deliver_first_tokens(self, fused_pair, admitted: List,
                              outs: List) -> None:
        """Emit the fused first tokens (one host sync, usually already
        in flight via copy_to_host_async)."""
        if fused_pair is None:
            return
        fused, fused_lp = fused_pair
        fused = np.asarray(fused)
        fused_lp = (np.asarray(fused_lp) if fused_lp is not None
                    else None)
        pos = 0
        now = time.monotonic()
        if admitted:
            for j, ((idx, _, _), tok) in enumerate(
                    zip(admitted, fused[:len(admitted)])):
                slot = self.slots[idx]
                if slot is None:  # drained by a concurrent stop()
                    continue
                tok = int(tok)
                slot.req.first_token_ts = now
                self._emit(slot, tok,
                           fused_lp[j] if fused_lp is not None
                           else None)
                if (tok == slot.req.eos_token
                        or slot.emitted >= slot.req.max_new_tokens):
                    self._finish(idx)
            pos = len(admitted)
        for reqs, toks, _ in outs:
            host = fused[pos:pos + toks.shape[0]]
            host_lp = (fused_lp[pos:pos + toks.shape[0]]
                       if fused_lp is not None else None)
            pos += toks.shape[0]
            for j, r in enumerate(reqs):
                tok = int(host[j])
                r.first_token_ts = now
                r._early_tok = tok
                r.tokens.append(tok)
                if host_lp is not None:
                    r.logprobs.append(float(host_lp[j]))
                r.stream.put(tok)
                self.tokens_out += 1
                if tok == r.eos_token or r.max_new_tokens <= 1:
                    # Finished before ever occupying a slot.
                    with self.lock:
                        try:
                            self.waiting.remove(r)
                        except ValueError:
                            continue  # already admitted concurrently
                    self._complete(r, len(r.tokens))

    def step(self) -> bool:
        """One engine tick: admit, serve queued requests' first tokens
        (cache-free path — TTFT does not wait for a slot), dispatch one
        fused block of decode steps for all slots, then process the
        PREVIOUS tick's block. The one-block pipeline means the host
        fetch of block N overlaps the device computing block N+1 —
        without it the chip idles a full host↔device round trip
        (~150 ms tunneled) per block, which dominates decode for small
        models. The on-device dependency chain (cache, cur_tokens) is
        exact; the host only lags by one block in observing tokens, so
        EOS/finish frees a slot one tick late (bounded overshoot, same
        class as mid-block overshoot). Returns False when idle."""
        with self._mesh_ctx():
            return self._step_impl()

    def _step_impl(self) -> bool:
        registered = (self._drain_auto_registrations()
                      if self.auto_prefix_min_hits > 0 else False)
        admitted = self._admit()
        outs = self._early_first_tokens()
        # Snapshot: a concurrent stop()/_fail_all may None-out entries
        # under us; every later access goes through the snapshot or
        # re-checks self.slots[i].
        fused = self._fuse_first_tokens(admitted, outs)
        snap = list(self.slots)
        active = [i for i, s in enumerate(snap) if s is not None]
        block = None
        if active:
            # Block size (adaptive, per step): sized to the minimum
            # remaining generation budget among active slots — counting
            # ticks already in flight — rounded UP to a power of two
            # (each distinct size is its own XLA compile): rounding
            # down would split a 63-token budget into ~7 dispatches and
            # pay the round trip for each; rounding up wastes at most
            # the finishing slot's share of the overshoot ticks. Capped
            # by self.decode_block (compile-cache/latency bound) and by
            # every slot's DEVICE-side cache headroom (length +
            # inflight) so no in-block write can run past max_seq_len.
            headroom = min(self.max_seq_len - 1
                           - snap[i].length - snap[i].inflight
                           for i in active)
            budget = max(snap[i].req.max_new_tokens - snap[i].emitted
                         - snap[i].inflight for i in active)
            if budget > 0 or self._pending is None:
                remaining = max(1, min(
                    max(1, snap[i].req.max_new_tokens - snap[i].emitted
                        - snap[i].inflight) for i in active))
                k_block = 1
                while k_block < remaining:
                    k_block *= 2
                k_block = min(k_block, self.decode_block,
                              max(1, headroom))
                while k_block & (k_block - 1):
                    k_block &= k_block - 1

                self._key, sub = jax.random.split(self._key)
                lps = None
                if k_block == 1:
                    self.cache, logits = decode_step(
                        self.cfg, self.params, self.cache,
                        self.cur_tokens)
                    if self.capture_logprobs:
                        toks, lps = _sample_batch_lp(
                            logits, self._temps, sub, self.top_k)
                        toks, lps = toks[None], lps[None]      # (1, B)
                    else:
                        toks = _sample_batch(logits, self._temps, sub,
                                             self.top_k)[None]  # (1, B)
                elif self.capture_logprobs:
                    self.cache, toks, lps = decode_multi_lp(
                        self.cfg, self.params, self.cache,
                        self.cur_tokens, self._temps, k_block,
                        self.top_k, sub)                       # (k, B)
                else:
                    self.cache, toks = decode_multi(
                        self.cfg, self.params, self.cache,
                        self.cur_tokens, self._temps, k_block,
                        self.top_k, sub)                       # (k, B)
                self.cur_tokens = toks[-1]
                # Start the host copy NOW, before the next tick enqueues
                # prefills/the next block: the tunnel serves plain fetch
                # responses only after ALL enqueued work, so a fetch
                # without the async copy would wait out work enqueued
                # AFTER the block it wants (measured 1.6s vs 0.37s per
                # 654M block).
                for arr in ((toks,) if lps is None else (toks, lps)):
                    try:
                        arr.copy_to_host_async()
                    except Exception:  # noqa: BLE001 — no async copy
                        pass
                self.decode_ticks += k_block
                for i in active:
                    snap[i].inflight += k_block
                block = (toks, lps, k_block,
                         [(i, snap[i]) for i in active])
            # else: every active slot's budget is already covered by
            # the in-flight block — dispatching more would only burn
            # wasted ticks; process the pending block instead.

        # First tokens (this step's admissions + queued requests) were
        # enqueued for copy before the block — emit them while the
        # block computes.
        self._deliver_first_tokens(fused, admitted, outs)
        prev, self._pending = self._pending, block
        if prev is not None:
            self._process_block(prev)
        return bool(admitted or outs or block or prev or registered)

    def _process_block(self, block) -> None:
        """Fetch a dispatched decode block's tokens and emit them.

        The block's slot snapshot carries the _Slot OBJECTS from
        dispatch time: a slot index freed and readmitted while the
        block was in flight now holds a different request, and the
        identity check keeps the dead request's overshoot tokens out
        of the new request's stream."""
        toks, lps, k_block, slot_snap = block
        host_toks = np.asarray(toks)
        host_lps = np.asarray(lps) if lps is not None else None
        for i, slot0 in slot_snap:
            slot0.inflight -= k_block
            slot = self.slots[i]
            if slot is not slot0:
                continue  # freed (and possibly readmitted) meanwhile
            for t in range(k_block):
                if slot is None or slot is not slot0:
                    break  # drained by stop() / finished below
                tok = int(host_toks[t, i])
                self._emit(slot, tok,
                           host_lps[t, i] if host_lps is not None
                           else None)
                done = (tok == slot.req.eos_token
                        or slot.emitted >= slot.req.max_new_tokens
                        or slot.length >= self.max_seq_len - 1)
                if done:
                    # Remaining in-block tokens for this slot are
                    # discarded; the slot frees for readmission.
                    self._finish(i)
                    break
                slot = self.slots[i]

    def run_forever(self) -> None:
        while not self._stop:
            try:
                busy = self.step()
            except Exception as e:  # noqa: BLE001 — device/XLA errors
                self._fail_all(e)
                raise
            if not busy:
                self._work.clear()
                self._work.wait(timeout=0.1)

    def _fail_all(self, exc: Exception) -> None:
        """A step blew up (OOM, XLA error): unblock every waiting client
        with the error instead of hanging their streams forever."""
        self._stop = True
        msg = f"{type(exc).__name__}: {exc}"
        with self.lock:
            pending = list(self.waiting)
            self.waiting.clear()
        for i, slot in enumerate(self.slots):
            if slot is not None:
                slot.req.error = msg
                slot.req.finish_ts = time.monotonic()
                slot.req.stream.put(None)
                self.slots[i] = None
        for req in pending:
            req.error = msg
            req.finish_ts = time.monotonic()
            req.stream.put(None)

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.run_forever, daemon=True,
                             name="llm-engine")
        self._loop_thread = t
        t.start()
        return t

    def stop(self) -> None:
        """Stop the engine and drain pending requests: every in-flight or
        waiting client gets an 'engine stopped' error instead of hanging
        on its stream."""
        self._stop = True
        self._work.set()
        self._fail_all(RuntimeError("engine stopped"))

    def stats(self) -> Dict[str, Any]:
        fin = self.finished
        ttfts = sorted(f["ttft_s"] for f in fin)
        out: Dict[str, Any] = {
            "finished": len(fin),
            "decode_ticks": self.decode_ticks,
            "tokens_out": self.tokens_out,
            "waiting": len(self.waiting),
            "active": sum(s is not None for s in self.slots),
            "prefix_hits": self.prefix_hits,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "cached_prefixes": len(self._prefixes),
        }
        if ttfts:
            out["ttft_p50_s"] = ttfts[len(ttfts) // 2]
            out["ttft_p99_s"] = ttfts[min(len(ttfts) - 1,
                                          int(len(ttfts) * 0.99))]
        if self._ttft_ewma is not None:
            out["ewma_ttft_s"] = self._ttft_ewma
        return out

    def serve_routing_stats(self) -> Dict[str, Any]:
        """Routing signals the serve Replica wrapper merges into its
        stats() payload (controller polls it, routers use it for
        queue-depth + TTFT-aware replica choice)."""
        out: Dict[str, Any] = {"engine_queue": len(self.waiting)}
        if self._ttft_ewma is not None:
            out["ewma_ttft_s"] = self._ttft_ewma
        return out


class LLMServer:
    """Serve deployment wrapper: one engine per replica, background
    loop. Use with @serve.deployment / serve.run; methods are invoked
    through DeploymentHandles."""

    def __init__(self, cfg: TransformerConfig, params: Any = None, *,
                 num_slots: int = 4, max_seq_len: Optional[int] = None,
                 seed: int = 0, auto_prefix_min_hits: int = 0,
                 auto_prefix_lens: Sequence[int] = (64, 128, 256, 512),
                 plan: Any = None,
                 mesh: Optional["jax.sharding.Mesh"] = None,
                 capture_logprobs: bool = False):
        if params is None:
            params = init_params(cfg, jax.random.key(seed))
        if mesh is None and plan is not None:
            # Replica-level sharding plan (tp/fsdp) → device mesh; the
            # deployment config carries the plan, each replica builds
            # its mesh from its own visible devices.
            from ..parallel import make_mesh
            mesh = make_mesh(plan)
        self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                max_seq_len=max_seq_len,
                                auto_prefix_min_hits=auto_prefix_min_hits,
                                auto_prefix_lens=auto_prefix_lens,
                                mesh=mesh,
                                capture_logprobs=capture_logprobs)
        self.engine.start()

    def generate(self, prompt: Sequence[int], *, max_new_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 return_logprobs: bool = False) -> Dict[str, Any]:
        return self.engine.generate(
            prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, eos_token=eos_token,
            return_logprobs=return_logprobs)

    def register_prefix(self, tokens: Sequence[int]) -> None:
        """Precompute a shared prompt prefix's KV on this replica."""
        self.engine.register_prefix(tokens)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def serve_routing_stats(self) -> Dict[str, Any]:
        """Merged into Replica.stats() → controller poll → router."""
        return self.engine.serve_routing_stats()


class LLMIngress:
    """Front deployment of the two-stage LLM app: takes the server's
    DeploymentHandle as an init arg (serve.run resolves the nested
    Application into the handle) and forwards generation requests —
    the composition pattern of the reference's serving app graphs
    (router/ingress -> engine deployment)."""

    def __init__(self, server):
        self.server = server

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 timeout: Optional[float] = 60.0) -> Dict[str, Any]:
        return self.server.generate.remote(
            prompt, max_new_tokens=max_new_tokens,
            temperature=temperature,
            eos_token=eos_token).result(timeout=timeout)

    def stats(self, *, timeout: Optional[float] = 60.0) -> Dict[str, Any]:
        return self.server.stats.remote().result(timeout=timeout)


@graphable(name="serve.llm_app")
def build_llm_app(cfg: TransformerConfig, *, num_slots: int = 4,
                  num_replicas: int = 1, seed: int = 0,
                  auto_prefix_min_hits: int = 0):
    """Build the LLM serving application graph: ingress -> server.

    The composition is declared with `.bind()` and materialized by
    `serve.run(...)`; `@graphable` marks it as a capture entry so
    raylint's graphcap pass extracts the deployment graph statically
    and tests/test_graph_capture.py verifies it against the
    controller's dynamic `app_graph()` view.
    """
    from .deployment import deployment

    server_dep = deployment(LLMServer, name="llm_server",
                            num_replicas=num_replicas)
    server_app = server_dep.bind(cfg, num_slots=num_slots, seed=seed,
                                 auto_prefix_min_hits=auto_prefix_min_hits)
    ingress_dep = deployment(LLMIngress, name="llm_ingress")
    return ingress_dep.bind(server_app)
