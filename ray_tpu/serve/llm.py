"""Continuous-batching LLM inference engine + serve deployment.

TPU-native serving path (SURVEY.md §7 step 9: "continuous batching
replica — KV cache in HBM, prefill/decode split — for the Llama-8B
serving target"; the reference delegates this entirely to vLLM,
doc/source/serve/doc_code/vllm_example.py).

Engine design around XLA's static shapes:
- a fixed pool of `num_slots` sequence slots backed by one static KV
  cache (models/generate.py); admission = prefill into a free slot,
  one bucketed-compile per prompt-length bucket;
- every engine tick runs ONE compiled decode step for ALL slots (the
  continuous-batching property: sequences join/leave between ticks,
  the compiled program never changes shape);
- per-slot temperature rides a (B,) operand, so mixed sampling configs
  share the tick; finished slots are ignored until readmission.

TTFT = submit→first-token (prefill-bound); per-request metrics are
recorded for the serving benchmark (BASELINE.md north-star: req/s +
p50 TTFT).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import (
    KVCache,
    decode_multi,
    decode_step,
    first_token_sample,
    init_kv_cache,
    prefill,
    prefill_sample,
)
from ..models.transformer import TransformerConfig, init_params


def default_buckets(max_prompt_len: int) -> List[int]:
    out, b = [], 16
    while b < max_prompt_len:
        out.append(b)
        b *= 2
    out.append(max_prompt_len)
    return out


@partial(jax.jit, static_argnums=(3,))
def _sample_batch(logits: jax.Array, temps: jax.Array, key: jax.Array,
                  top_k: int) -> jax.Array:
    """(B,V) logits -> (B,) tokens; temp<=0 slots decode greedily."""
    from ..models.generate import sample

    return sample(logits, key, temperature=temps, top_k=top_k)


@dataclass
class GenRequest:
    prompt: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    eos_token: Optional[int] = None
    # filled by the engine
    id: int = 0
    submit_ts: float = 0.0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    stream: "queue.Queue" = field(default_factory=queue.Queue)
    tokens: List[int] = field(default_factory=list)
    error: Optional[str] = None
    # Set once the terminal None has been consumed (engine-internal).
    _done: bool = field(default=False, repr=False)
    # First token served queue-side before any slot freed (engine-
    # internal; _admit resumes decode from it).
    _early_tok: Optional[int] = field(default=None, repr=False)

    @property
    def ttft_s(self) -> float:
        return self.first_token_ts - self.submit_ts

    @property
    def latency_s(self) -> float:
        return self.finish_ts - self.submit_ts

    def __iter__(self) -> Iterator[int]:
        if self._done:
            # Replay: the stream was already drained (by result() or a
            # prior iteration) — blocking on it again would hang.
            if self.error is not None:
                raise RuntimeError(f"generation failed: {self.error}")
            yield from list(self.tokens)
            return
        while True:
            tok = self.stream.get()
            if tok is None:
                self._done = True  # result() must not block after this
                if self.error is not None:
                    raise RuntimeError(f"generation failed: {self.error}")
                return
            yield tok

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """ALL generated tokens, regardless of how many were already
        consumed via streaming — idempotent and safe after __iter__."""
        if self._done:
            if self.error is not None:
                raise RuntimeError(f"generation failed: {self.error}")
            return list(self.tokens)
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            left = (max(0.0, deadline - time.monotonic())
                    if deadline is not None else None)
            tok = self.stream.get(timeout=left)
            if tok is None:
                self._done = True
                if self.error is not None:
                    raise RuntimeError(
                        f"generation failed: {self.error}")
                # self.tokens has every emitted token (the engine
                # appends there before the stream put).
                return list(self.tokens)


class _Slot:
    __slots__ = ("req", "emitted", "length")

    def __init__(self, req: GenRequest, prompt_len: int):
        self.req = req
        self.emitted = 0
        self.length = prompt_len  # tokens in cache (grows per tick)


class LLMEngine:
    """Host-side continuous-batching loop over the compiled
    prefill/decode steps. Thread-safe submit; `step()` is driven either
    by `run_forever()` (background thread) or manually (tests)."""

    def __init__(self, cfg: TransformerConfig, params: Any, *,
                 num_slots: int = 4, max_seq_len: Optional[int] = None,
                 top_k: int = 0, seed: int = 0, decode_block: int = 64):
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        self.top_k = top_k
        # UPPER BOUND on ticks fused per dispatch (decode_multi); the
        # actual block size adapts ONLINE each step to the minimum
        # remaining generation budget among active slots, so a block
        # ends exactly when the first slot completes and its
        # replacement is admitted (no workload-tuned constant — the cap
        # only bounds the compile cache and worst-case admission
        # latency). Bigger fused blocks amortize the host↔device round
        # trip (~150 ms on a tunneled chip).
        self.decode_block = max(1, decode_block)
        self.cache: KVCache = init_kv_cache(cfg, num_slots, self.max_seq_len)
        self.cur_tokens = jnp.zeros((num_slots,), jnp.int32)
        # Device-resident per-slot temperatures: updated by scatter at
        # admission, never re-uploaded per tick.
        self._temps = jnp.zeros((num_slots,), jnp.float32)
        self._key = jax.random.key(seed)
        self.slots: List[Optional[_Slot]] = [None] * num_slots
        self.waiting: deque = deque()
        self.lock = threading.Lock()
        self._work = threading.Event()
        self._stop = False
        self._next_id = 0
        self.buckets = default_buckets(self.max_seq_len)
        # aggregate stats
        self.decode_ticks = 0
        self.tokens_out = 0
        self.finished: List[Dict[str, float]] = []

    # -- submission ---------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 64,
               temperature: float = 0.0,
               eos_token: Optional[int] = None) -> GenRequest:
        if self._stop:
            raise RuntimeError("engine is stopped")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) >= self.max_seq_len:
            raise ValueError(
                f"prompt len {len(prompt)} >= max_seq_len {self.max_seq_len}")
        req = GenRequest(prompt=list(prompt), max_new_tokens=max_new_tokens,
                         temperature=temperature, eos_token=eos_token)
        with self.lock:
            req.id = self._next_id
            self._next_id += 1
        req.submit_ts = time.monotonic()
        with self.lock:
            self.waiting.append(req)
        self._work.set()
        return req

    # -- engine internals ---------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _emit(self, slot: _Slot, tok: int) -> None:
        slot.req.tokens.append(tok)
        slot.req.stream.put(tok)
        slot.emitted += 1
        slot.length += 1
        self.tokens_out += 1

    def _complete(self, req: GenRequest, new_tokens: int) -> None:
        """Single place for request-completion bookkeeping (slot-path
        and queue-side finishes alike)."""
        req.finish_ts = time.monotonic()
        req.stream.put(None)
        self.finished.append({
            "id": req.id,
            "ttft_s": req.ttft_s,
            "latency_s": req.latency_s,
            "new_tokens": new_tokens,
        })

    def _finish(self, idx: int) -> None:
        slot = self.slots[idx]
        self._complete(slot.req, slot.emitted)
        self.slots[idx] = None

    def _pad_prompt(self, req: GenRequest) -> Any:
        """Pad on the HOST: an eager .at[:plen].set() compiles a
        scatter kernel per distinct prompt length (seconds each),
        wrecking admission latency; numpy + one transfer doesn't."""
        plen = len(req.prompt)
        bucket = self._bucket_for(plen)
        buf = np.zeros((1, bucket), np.int32)
        buf[0, :plen] = np.asarray(req.prompt, np.int32)
        return jnp.asarray(buf)

    def _admit(self) -> List:
        """Prefill waiting requests into free slots (arrival order).

        All admissions are DISPATCHED here (async); the first tokens
        are fetched later by _deliver_first_tokens with one fused host
        sync — on remote/tunneled chips each sync costs a full round
        trip. Requests whose first token was already served by
        _early_first_tokens() are prefilled without sampling and their
        decode continues from that token. Returns [(idx, tok_dev)].
        """
        admitted: List = []  # (idx, tok_dev) — first token pending
        while True:
            with self.lock:
                free = [i for i, s in enumerate(self.slots) if s is None]
                if not free or not self.waiting:
                    break
                req = self.waiting.popleft()
            idx = free[0]
            plen = len(req.prompt)
            padded = self._pad_prompt(req)
            early_tok = getattr(req, "_early_tok", None)
            try:
                if early_tok is not None:
                    # First token already delivered queue-side: write
                    # the prompt KV only; decode continues from the
                    # token the client saw.
                    self.cache, _last = prefill(
                        self.cfg, self.params, self.cache, padded,
                        jnp.int32(plen), jnp.int32(idx))
                else:
                    self._key, sub = jax.random.split(self._key)
                    # prefill + first-token sample in one dispatch.
                    self.cache, tok_dev = prefill_sample(
                        self.cfg, self.params, self.cache, padded,
                        jnp.int32(plen), jnp.int32(idx), self.top_k,
                        jnp.float32(req.temperature), sub)
            except Exception:
                # put it back so _fail_all can notify its client
                with self.lock:
                    self.waiting.appendleft(req)
                raise
            slot = _Slot(req, plen)
            self.slots[idx] = slot
            self._temps = self._temps.at[idx].set(req.temperature)
            if early_tok is not None:
                slot.emitted = len(req.tokens)
                slot.length = plen + slot.emitted
                self.cur_tokens = self.cur_tokens.at[idx].set(
                    int(early_tok))
            else:
                self.cur_tokens = self.cur_tokens.at[idx].set(tok_dev)
                admitted.append((idx, tok_dev))
        return admitted

    def _early_first_tokens(self) -> List:
        """TTFT decoupled from slot availability: queued requests that
        could not be admitted get their FIRST token from a cache-free
        batched forward (models/generate.first_token_sample), in
        arrival order, one dispatch per prompt-bucket tile. When a slot
        frees, _admit prefills the prompt and decode resumes from this
        token — the client's stream stays consistent. Returns
        [(chunk_requests, toks_dev)]; fetched by
        _deliver_first_tokens."""
        with self.lock:
            todo = [r for r in self.waiting
                    if r.first_token_ts == 0.0]
        if not todo:
            return []
        by_bucket: Dict[int, List[GenRequest]] = {}
        for r in todo:
            by_bucket.setdefault(
                self._bucket_for(len(r.prompt)), []).append(r)
        outs = []
        W = 8  # fixed batch tile: ONE compile per bucket, ever
        for bucket, reqs in sorted(by_bucket.items()):
            for off in range(0, len(reqs), W):
                chunk = reqs[off:off + W]
                buf = np.zeros((W, bucket), np.int32)
                lens = np.ones((W,), np.int32)
                temps = np.zeros((W,), np.float32)
                for j, r in enumerate(chunk):
                    pl = len(r.prompt)
                    buf[j, :pl] = np.asarray(r.prompt, np.int32)
                    lens[j] = pl
                    temps[j] = r.temperature
                self._key, sub = jax.random.split(self._key)
                toks = first_token_sample(
                    self.cfg, self.params, jnp.asarray(buf),
                    jnp.asarray(lens), jnp.asarray(temps), self.top_k,
                    sub)
                outs.append((chunk, toks))
        return outs

    def _fuse_first_tokens(self, admitted: List, outs: List):
        """Concatenate every pending first token into ONE device array
        and start its host copy — enqueued BEFORE the decode block so
        the device serves it first (device execution is in-order; a
        fetch enqueued after the block would wait out the whole
        block)."""
        if not admitted and not outs:
            return None
        parts = []
        if admitted:
            parts.append(jnp.stack([t for _, t in admitted]))
        parts += [t for _, t in outs]
        fused = jnp.concatenate(parts)
        try:
            fused.copy_to_host_async()
        except Exception:  # noqa: BLE001 — backend without async copy
            pass
        return fused

    def _deliver_first_tokens(self, fused, admitted: List,
                              outs: List) -> None:
        """Emit the fused first tokens (one host sync, usually already
        in flight via copy_to_host_async)."""
        if fused is None:
            return
        fused = np.asarray(fused)
        pos = 0
        now = time.monotonic()
        if admitted:
            for (idx, _), tok in zip(admitted,
                                     fused[:len(admitted)]):
                slot = self.slots[idx]
                if slot is None:  # drained by a concurrent stop()
                    continue
                tok = int(tok)
                slot.req.first_token_ts = now
                self._emit(slot, tok)
                if (tok == slot.req.eos_token
                        or slot.emitted >= slot.req.max_new_tokens):
                    self._finish(idx)
            pos = len(admitted)
        for reqs, toks in outs:
            host = fused[pos:pos + toks.shape[0]]
            pos += toks.shape[0]
            for j, r in enumerate(reqs):
                tok = int(host[j])
                r.first_token_ts = now
                r._early_tok = tok
                r.tokens.append(tok)
                r.stream.put(tok)
                self.tokens_out += 1
                if tok == r.eos_token or r.max_new_tokens <= 1:
                    # Finished before ever occupying a slot.
                    with self.lock:
                        try:
                            self.waiting.remove(r)
                        except ValueError:
                            continue  # already admitted concurrently
                    self._complete(r, len(r.tokens))

    def step(self) -> bool:
        """One engine tick: admit, serve queued requests' first tokens
        (cache-free path — TTFT does not wait for a slot), then one
        fused block of decode steps for all slots. All device work is
        dispatched before any host fetch, so round trips overlap
        compute. Returns False when there is nothing to do."""
        admitted = self._admit()
        outs = self._early_first_tokens()
        # Snapshot: a concurrent stop()/_fail_all may None-out entries
        # under us; every later access goes through the snapshot or
        # re-checks self.slots[i].
        fused = self._fuse_first_tokens(admitted, outs)
        snap = list(self.slots)
        active = [i for i, s in enumerate(snap) if s is not None]
        if not active:
            self._deliver_first_tokens(fused, admitted, outs)
            return bool(admitted or outs)

        # Block size (adaptive, per step): sized to the minimum
        # remaining generation budget among active slots, rounded UP to
        # a power of two (each distinct size is its own XLA compile) —
        # rounding down would split a 63-token budget into ~7 dispatches
        # and pay the host↔device round trip for each; rounding up
        # wastes at most the finishing slot's share of the overshoot
        # ticks. Capped by self.decode_block (compile-cache/latency
        # bound) and by every slot's cache headroom so no in-block
        # write can run past max_seq_len.
        headroom = min(self.max_seq_len - 1 - snap[i].length
                       for i in active)
        remaining = max(1, min(snap[i].req.max_new_tokens
                               - snap[i].emitted for i in active))
        k_block = 1
        while k_block < remaining:
            k_block *= 2
        k_block = min(k_block, self.decode_block, max(1, headroom))
        while k_block & (k_block - 1):
            k_block &= k_block - 1

        self._key, sub = jax.random.split(self._key)
        if k_block == 1:
            self.cache, logits = decode_step(
                self.cfg, self.params, self.cache, self.cur_tokens)
            toks = _sample_batch(logits, self._temps, sub,
                                 self.top_k)[None]         # (1, B)
        else:
            self.cache, toks = decode_multi(
                self.cfg, self.params, self.cache, self.cur_tokens,
                self._temps, k_block, self.top_k, sub)     # (k, B)
        self.cur_tokens = toks[-1]
        # First tokens (this step's admissions + queued requests) were
        # enqueued for copy before the block — emit them while the
        # block computes.
        self._deliver_first_tokens(fused, admitted, outs)
        host_toks = np.asarray(toks)
        self.decode_ticks += k_block

        for i in active:
            slot = self.slots[i]
            for t in range(k_block):
                if slot is None:  # drained by a concurrent stop()
                    break
                tok = int(host_toks[t, i])
                self._emit(slot, tok)
                done = (tok == slot.req.eos_token
                        or slot.emitted >= slot.req.max_new_tokens
                        or slot.length >= self.max_seq_len - 1)
                if done:
                    # Remaining in-block tokens for this slot are
                    # discarded; the slot frees for readmission.
                    self._finish(i)
                    break
                slot = self.slots[i]
        return True

    def run_forever(self) -> None:
        while not self._stop:
            try:
                busy = self.step()
            except Exception as e:  # noqa: BLE001 — device/XLA errors
                self._fail_all(e)
                raise
            if not busy:
                self._work.clear()
                self._work.wait(timeout=0.1)

    def _fail_all(self, exc: Exception) -> None:
        """A step blew up (OOM, XLA error): unblock every waiting client
        with the error instead of hanging their streams forever."""
        self._stop = True
        msg = f"{type(exc).__name__}: {exc}"
        with self.lock:
            pending = list(self.waiting)
            self.waiting.clear()
        for i, slot in enumerate(self.slots):
            if slot is not None:
                slot.req.error = msg
                slot.req.finish_ts = time.monotonic()
                slot.req.stream.put(None)
                self.slots[i] = None
        for req in pending:
            req.error = msg
            req.finish_ts = time.monotonic()
            req.stream.put(None)

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.run_forever, daemon=True,
                             name="llm-engine")
        t.start()
        return t

    def stop(self) -> None:
        """Stop the engine and drain pending requests: every in-flight or
        waiting client gets an 'engine stopped' error instead of hanging
        on its stream."""
        self._stop = True
        self._work.set()
        self._fail_all(RuntimeError("engine stopped"))

    def stats(self) -> Dict[str, Any]:
        fin = self.finished
        ttfts = sorted(f["ttft_s"] for f in fin)
        out: Dict[str, Any] = {
            "finished": len(fin),
            "decode_ticks": self.decode_ticks,
            "tokens_out": self.tokens_out,
            "waiting": len(self.waiting),
            "active": sum(s is not None for s in self.slots),
        }
        if ttfts:
            out["ttft_p50_s"] = ttfts[len(ttfts) // 2]
            out["ttft_p99_s"] = ttfts[min(len(ttfts) - 1,
                                          int(len(ttfts) * 0.99))]
        return out


class LLMServer:
    """Serve deployment wrapper: one engine per replica, background
    loop. Use with @serve.deployment / serve.run; methods are invoked
    through DeploymentHandles."""

    def __init__(self, cfg: TransformerConfig, params: Any = None, *,
                 num_slots: int = 4, max_seq_len: Optional[int] = None,
                 seed: int = 0):
        if params is None:
            params = init_params(cfg, jax.random.key(seed))
        self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                max_seq_len=max_seq_len)
        self.engine.start()

    def generate(self, prompt: Sequence[int], *, max_new_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_token: Optional[int] = None) -> Dict[str, Any]:
        req = self.engine.submit(
            prompt, max_new_tokens=max_new_tokens, temperature=temperature,
            eos_token=eos_token)
        tokens = req.result()
        return {"tokens": tokens, "ttft_s": req.ttft_s,
                "latency_s": req.latency_s}

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()
