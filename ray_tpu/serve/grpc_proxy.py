"""gRPC ingress proxy.

Capability-equivalent to the reference's gRPC proxy
(reference: python/ray/serve/_private/proxy.py:547 gRPCProxy — a
grpc.aio server routing RPCs to deployment handles by app name, with
the application selected via request metadata). Served without protoc:
a GenericRpcHandler exposes one service

    /ray_tpu.serve.GenericService/Predict

taking a pickled request payload and returning the pickled result; the
target application comes from the ``application`` metadata key (the
reference uses the same metadata convention). Wire compat with Ray's
per-user-proto servicers is not a goal — the capability (gRPC ingress
into deployments) is.
"""

from __future__ import annotations

import pickle
import threading
from concurrent import futures
from typing import Any, Dict, Optional

SERVICE = "ray_tpu.serve.GenericService"
METHOD = f"/{SERVICE}/Predict"


# Payloads off the network may contain plain data + numpy arrays (the
# normal inference request/response shape) and nothing else — anything
# resolvable through find_class can execute code via __reduce__.
_SAFE_CLASSES = {
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "scalar"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    """Resolve only the numpy allow-list; refuse everything else."""

    def find_class(self, module, name):
        if (module, name) in _SAFE_CLASSES:
            import importlib

            return getattr(importlib.import_module(module), name)
        raise pickle.UnpicklingError(
            f"payloads must be plain data (+ numpy arrays); refusing "
            f"{module}.{name}")


def _restricted_loads(data: bytes):
    import io

    return _RestrictedUnpickler(io.BytesIO(data)).load()


class GrpcProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        self.host = host
        self.port = port
        self._routes: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._server = None

    def add_route(self, name: str, handle) -> None:
        with self._lock:
            self._routes[name.strip("/")] = handle

    def remove_route(self, name: str) -> None:
        with self._lock:
            self._routes.pop(name.strip("/"), None)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "GrpcProxy":
        import grpc

        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != METHOD:
                    return None
                meta = dict(handler_call_details.invocation_metadata)

                def unary(request_bytes, context):
                    return proxy._handle(request_bytes, meta, context)

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b)

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((Handler(),))
        requested = self.port
        bound = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        if bound == 0:
            # grpc reports bind failure by returning port 0.
            self._server = None
            raise OSError(
                f"gRPC proxy could not bind {self.host}:{requested}")
        self.port = bound
        self._server.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)
            self._server = None

    # -- request path ---------------------------------------------------
    def _handle(self, request_bytes: bytes, meta: Dict[str, str],
                context) -> bytes:
        import grpc

        app = meta.get("application", "").strip("/")
        with self._lock:
            handle = self._routes.get(app)
        if handle is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no application {app!r}")
        try:
            payload = _restricted_loads(request_bytes)
        except Exception:  # noqa: BLE001
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "request must be a pickled plain-data payload "
                          "(dict/list/str/num/bytes — no custom classes)")
        # Honor the client's deadline (default 30s when none given).
        remaining = context.time_remaining()
        timeout = remaining if remaining is not None else 30.0
        try:
            result = handle.remote(payload).result(timeout=timeout)
        except BaseException as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e)[:500])
        return pickle.dumps(result)


class GrpcClient:
    """Convenience client for the generic service (tests / quick use;
    any gRPC stack can call the method directly)."""

    def __init__(self, address: str):
        import grpc

        self._channel = grpc.insecure_channel(address)
        self._call = self._channel.unary_unary(
            METHOD,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)

    def predict(self, application: str, payload: Any,
                timeout: float = 30.0) -> Any:
        out = self._call(pickle.dumps(payload),
                         metadata=(("application", application),),
                         timeout=timeout)
        # Responses get the same restricted unpickling as requests: the
        # channel is insecure, so the peer is untrusted by default.
        return _restricted_loads(out)

    def close(self) -> None:
        self._channel.close()
