"""Declarative (YAML) serve config deploy.

Capability-equivalent of the reference's config-file workflow
(reference: python/ray/serve/schema.py ServeDeploySchema /
ServeApplicationSchema; `serve deploy config.yaml` + `serve status` CLI
in serve/scripts.py; REST via dashboard modules/serve): a config lists
applications by import path with per-deployment overrides; applying it
builds and runs each app. Shape:

    http_options:
      port: 8000
    grpc_options:
      port: 9000
    applications:
      - name: app1
        route_prefix: app1
        import_path: my_module:app          # Application or Deployment
        deployments:                        # optional overrides
          - name: MyDeployment
            num_replicas: 2
            max_ongoing_requests: 64
            autoscaling_config: {min_replicas: 1, max_replicas: 4}
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from .deployment import Application, Deployment


def build_app(import_path: str) -> Application:
    """'module.sub:attr' → bound Application (a bare Deployment gets
    .bind()ed with no args; reference: serve.build import paths)."""
    module_name, _, attr = import_path.partition(":")
    if not module_name or not attr:
        raise ValueError(
            f"import_path must be 'module:attribute', got "
            f"{import_path!r}")
    obj = importlib.import_module(module_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if isinstance(obj, Application):
        return obj
    if isinstance(obj, Deployment):
        return obj.bind()
    if callable(obj):
        built = obj()
        if isinstance(built, Application):
            return built
    raise TypeError(
        f"{import_path} must resolve to an Application, a Deployment, "
        f"or a zero-arg builder returning an Application")


def _apply_overrides(app: Application,
                     overrides: List[Dict[str, Any]]) -> None:
    """Mutate the app graph's deployment configs per the config file."""
    by_name = {node.deployment.name: node for node in app.flatten()}
    for ov in overrides or []:
        name = ov.get("name")
        node = by_name.get(name)
        if node is None:
            raise ValueError(
                f"config overrides unknown deployment {name!r}; "
                f"have {sorted(by_name)}")
        node.deployment = node.deployment.options(
            num_replicas=ov.get("num_replicas"),
            max_ongoing_requests=ov.get("max_ongoing_requests"),
            max_queued_requests=ov.get("max_queued_requests"),
            max_request_retries=ov.get("max_request_retries"),
            health_check_period_s=ov.get("health_check_period_s"),
            health_check_timeout_s=ov.get("health_check_timeout_s"),
            autoscaling_config=ov.get("autoscaling_config"),
            ray_actor_options=ov.get("ray_actor_options"),
            user_config=ov.get("user_config"))


def apply_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Deploy every application in the config (reference:
    `serve deploy`). Returns {app_name: route}."""
    from . import api

    apps = config.get("applications") or []
    if not apps:
        raise ValueError("config has no applications")
    # Reference default: HTTP ingress is ON for config deploys — apps
    # deployed from a file with no ingress at all would be unreachable.
    http_opts = config.get("http_options")
    grpc_opts = config.get("grpc_options") or {}
    if http_opts is None and not grpc_opts:
        http_opts = {"enabled": True}
    http_opts = http_opts or {}
    out: Dict[str, Any] = {}
    for spec in apps:
        name = spec.get("name") or "default"
        app = build_app(spec["import_path"])
        _apply_overrides(app, spec.get("deployments"))
        api.run(
            app, name=name,
            route_prefix=spec.get("route_prefix"),
            http="port" in http_opts or bool(http_opts.get("enabled")),
            http_port=int(http_opts.get("port", 8000)),
            grpc="port" in grpc_opts or bool(grpc_opts.get("enabled")),
            grpc_port=int(grpc_opts.get("port", 9000)))
        out[name] = spec.get("route_prefix") or name
    return out


def apply_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return apply_config(yaml.safe_load(f))
