"""@serve.batch — transparent request batching.

Capability-equivalent to the reference's batching
(reference: python/ray/serve/batching.py:65 _BatchQueue + @serve.batch):
calls buffer up to max_batch_size or batch_wait_timeout_s, then the
wrapped function runs once on the list of requests; each caller gets its
element back. On TPU this is what keeps the MXU fed with batched matmuls
instead of batch-1 calls.

Implementation note: the wrappers close over only picklable config (the
queue/lock state lives in a process-local registry), so batched methods
survive cloudpickle into replica actors.
"""

from __future__ import annotations

import functools
import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

# Process-local state registries (never pickled — module globals).
_registry_lock = threading.Lock()
_batch_queues: Dict[Tuple[int, str], "_BatchQueue"] = {}
_mux_caches: Dict[Tuple[int, str], "_MuxCache"] = {}


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._items: List[Any] = []
        self._futures: List[Future] = []
        self._timer: Optional[threading.Timer] = None

    def submit(self, item, instance=None) -> Future:
        fut: Future = Future()
        flush_now = False
        with self._lock:
            self._items.append(item)
            self._futures.append(fut)
            if len(self._items) >= self.max_batch_size:
                flush_now = True
            elif self._timer is None:
                self._timer = threading.Timer(
                    self.timeout, self._flush, args=(instance,))
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush(instance)
        return fut

    def _flush(self, instance=None):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            items, futures = self._items, self._futures
            self._items, self._futures = [], []
        if not items:
            return
        try:
            if instance is not None:
                results = self.fn(instance, items)
            else:
                results = self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for {len(items)} inputs")
            for f, r in zip(futures, results):
                f.set_result(r)
        except BaseException as e:  # noqa: BLE001
            for f in futures:
                if not f.done():
                    f.set_exception(e)


def _get_batch_queue(fn, instance, max_batch_size, timeout) -> _BatchQueue:
    key = (id(instance) if instance is not None else 0,
           getattr(fn, "__qualname__", repr(fn)))
    with _registry_lock:
        q = _batch_queues.get(key)
        if q is None:
            q = _BatchQueue(fn, max_batch_size, timeout)
            _batch_queues[key] = q
        return q


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(self, items: list) -> list (method) or
    fn(items) -> list (function). Callers invoke with a single item and
    block for their single result."""

    def wrap(fn):
        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:          # bound method call: (self, item)
                instance, item = args
            else:
                (item,) = args
                instance = None
            q = _get_batch_queue(
                fn, instance, max_batch_size, batch_wait_timeout_s)
            return q.submit(item, instance).result()

        wrapper._ray_tpu_batched = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


class _MuxCache:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.lock = threading.Lock()
        self.cache: Dict[str, Any] = {}
        self.order: List[str] = []


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """@serve.multiplexed — per-replica LRU model cache
    (reference: serve/api.py:569 + serve/multiplex.py)."""

    def wrap(fn):
        @functools.wraps(fn)
        def wrapper(*args):
            *head, model_id = args
            instance = head[0] if head else None
            key = (id(instance) if instance is not None else 0,
                   getattr(fn, "__qualname__", repr(fn)))
            with _registry_lock:
                state = _mux_caches.get(key)
                if state is None:
                    state = _MuxCache(max_num_models_per_replica)
                    _mux_caches[key] = state
            with state.lock:
                if model_id in state.cache:
                    state.order.remove(model_id)
                    state.order.append(model_id)
                    return state.cache[model_id]
            model = fn(*args)
            with state.lock:
                state.cache[model_id] = model
                state.order.append(model_id)
                while len(state.order) > state.capacity:
                    evict = state.order.pop(0)
                    state.cache.pop(evict, None)
            return model

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
