"""Autoscaler v2 — control-plane-owned autoscaling state.

Capability-equivalent of the reference's autoscaler v2 (reference:
python/ray/autoscaler/v2/ + src/ray/gcs/gcs_server/
gcs_autoscaler_state_manager.h — the GCS owns the authoritative
cluster-state view: resource demand, node states; the autoscaler reads
it from there rather than living inside one driver's runtime):

- every DRIVER publishes its pending demand to the control plane's KV
  (`_as/demand/<driver>`, refreshed by the RemotePlane poll loop and
  deleted on shutdown);
- node daemons already report load via heartbeats (LIST_NODES);
- `MonitorV2` merges the cluster-wide view and drives the SAME
  bin-packing/reconciliation logic as v1 (StandardAutoscaler) through a
  control-plane-backed adapter — so a cluster with many drivers (or no
  driver at all) still scales on total demand.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core.resources import ResourceSet

DEMAND_PREFIX = "_as/demand/"
DEMAND_STALE_S = 15.0


def serialize_demand(detailed: List[Tuple]) -> str:
    """[(ResourceSet, hard, selector)] → JSON for the KV."""
    return json.dumps({
        "ts": time.time(),
        "demand": [
            {"resources": rs.to_dict(), "hard": bool(hard),
             "selector": dict(selector or {})}
            for rs, hard, selector in detailed
        ],
    })


class _NodeView:
    """Duck-typed NodeState for the autoscaler's read paths."""

    def __init__(self, node_id: str, total: ResourceSet,
                 available: ResourceSet, labels: Dict[str, str],
                 alive: bool):
        self.node_id = node_id
        self.total = total
        self.available = available
        self.labels = labels
        self.alive = alive


class ControlPlaneView:
    """The scheduler-shaped adapter over control-plane state: what the
    v1 autoscaler reads (`pending_demand_detailed`, `pending_demand`,
    `nodes`), sourced cluster-wide instead of from one driver."""

    def __init__(self, control_client):
        self.control = control_client

    def pending_demand_detailed(self) -> List[Tuple]:
        out: List[Tuple] = []
        now = time.time()
        try:
            keys = self.control.kv_keys(DEMAND_PREFIX)
        except Exception:  # noqa: BLE001 — control plane hiccup
            return out
        for key in keys:
            try:
                doc = json.loads(self.control.kv_get(key))
            except Exception:  # noqa: BLE001 — racing delete/corrupt
                continue
            if now - float(doc.get("ts", 0)) > DEMAND_STALE_S:
                continue  # dead driver's report
            for d in doc.get("demand", []):
                out.append((ResourceSet(d.get("resources", {})),
                            bool(d.get("hard")),
                            dict(d.get("selector") or {})))
        return out

    def pending_demand(self) -> List[ResourceSet]:
        return [rs for rs, _h, _s in self.pending_demand_detailed()]

    def nodes(self) -> List[_NodeView]:
        out = []
        try:
            rows = self.control.list_nodes()
        except Exception:  # noqa: BLE001
            return out
        for n in rows:
            try:
                meta = json.loads(n["meta"]) if n["meta"] else {}
            except ValueError:
                meta = {}
            if meta.get("node_kind") != "daemon":
                continue
            total = ResourceSet(meta.get("resources", {}))
            available = total
            if n.get("load"):
                try:
                    load = json.loads(n["load"])
                    available = ResourceSet(load.get("available", {}))
                except ValueError:
                    pass
            out.append(_NodeView(
                n["node_id"], total, available,
                dict(meta.get("labels") or {}), bool(n["alive"])))
        return [v for v in out if v.alive]


class _ViewRuntime:
    """What StandardAutoscaler expects of `runtime`."""

    def __init__(self, view: ControlPlaneView):
        self.scheduler = view


class MonitorV2:
    """The reconciliation loop over control-plane-owned state
    (reference: autoscaler/v2 instance_manager + the Monitor role).
    Reuses v1's bin-packing/min-max/idle logic unchanged — only the
    STATE SOURCE moves to the control plane."""

    def __init__(self, control_client, config, provider,
                 on_node_launched=None):
        from .autoscaler import StandardAutoscaler

        self.view = ControlPlaneView(control_client)
        self.autoscaler = StandardAutoscaler(
            config, provider, runtime=_ViewRuntime(self.view),
            on_node_launched=on_node_launched)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def update(self) -> Dict[str, int]:
        return self.autoscaler.update()

    def start(self, interval_s: float = 5.0) -> "MonitorV2":
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.update()
                except Exception:  # noqa: BLE001 — keep reconciling
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler-v2")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
