"""Cloud node providers + command runners.

Capability-equivalent to the reference's cloud provider tree
(reference: autoscaler/_private/gcp/node_provider.py, aws/, command_runner.py
SSHCommandRunner). TPU-first: the flagship provider launches **TPU VM
pods** through the Cloud TPU REST API (tpu.googleapis.com) rather than
GPU instances through GCE; one "node" is a TPU host with its chips as
schedulable resources.

All HTTP goes through an injectable transport so the provider logic is
fully testable without credentials or egress (the reference tests its
providers the same way — mocked cloud APIs, autoscaler_test_utils).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .autoscaler import NodeProvider

logger = logging.getLogger("ray_tpu")

# transport(method, url, body_dict_or_none, headers) -> (status, body_dict)
Transport = Callable[[str, str, Optional[dict], Dict[str, str]],
                     Tuple[int, dict]]

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")


def _default_transport(method: str, url: str, body: Optional[dict],
                       headers: Dict[str, str]) -> Tuple[int, dict]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **headers})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
            return resp.status, (json.loads(payload) if payload else {})
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            return e.code, json.loads(payload)
        except Exception:  # noqa: BLE001
            return e.code, {"error": payload.decode(errors="replace")}


def _metadata_token(transport: Transport) -> str:
    status, body = transport("GET", _METADATA_TOKEN_URL, None,
                             {"Metadata-Flavor": "Google"})
    if status != 200 or "access_token" not in body:
        raise RuntimeError(
            "no GCE service-account token available (not on GCE / no "
            "scopes); pass token= or transport= to GceTpuNodeProvider")
    return body["access_token"]


class GceTpuNodeProvider(NodeProvider):
    """Launches/terminates TPU VM nodes via the Cloud TPU v2 API.

    One autoscaler node == one TPU pod slice (`accelerator_type`, e.g.
    "v5litepod-8"); `cluster_name` labels every node so
    non_terminated_nodes only sees this cluster's machines.
    """

    def __init__(self, project: str, zone: str, cluster_name: str, *,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 node_configs: Optional[Dict[str, dict]] = None,
                 token: Optional[str] = None,
                 transport: Optional[Transport] = None,
                 poll_interval_s: float = 2.0):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        # Per-node-type launch params (accelerator_type/runtime_version
        # overrides) keyed by node type name — from the cluster YAML's
        # available_node_types[*].node_config.
        self.node_configs = dict(node_configs or {})
        self.poll_interval_s = poll_interval_s
        self._transport = transport or _default_transport
        self._token = token
        self._counter = 0
        # node_id -> labels, filled by the list call so per-node lookups
        # (node_type_of) don't each cost a REST GET.
        self._label_cache: Dict[str, Dict[str, str]] = {}

    # -- REST plumbing -------------------------------------------------

    @property
    def _base(self) -> str:
        return (f"https://tpu.googleapis.com/v2/projects/{self.project}"
                f"/locations/{self.zone}")

    def _headers(self) -> Dict[str, str]:
        if self._token is None:
            self._token = _metadata_token(self._transport)
        return {"Authorization": f"Bearer {self._token}"}

    def _call(self, method: str, path: str,
              body: Optional[dict] = None) -> dict:
        status, payload = self._transport(
            method, f"{self._base}/{path}", body, self._headers())
        if status == 401:  # token expired: refresh once
            self._token = None
            status, payload = self._transport(
                method, f"{self._base}/{path}", body, self._headers())
        if status >= 300:
            raise RuntimeError(
                f"TPU API {method} {path} failed ({status}): {payload}")
        return payload

    # -- NodeProvider --------------------------------------------------

    def create_node(self, resources: Dict[str, float],
                    labels: Dict[str, str],
                    node_type: str = "") -> str:
        self._counter += 1
        node_id = f"{self.cluster_name}-w{self._counter}-{int(time.time())}"
        node_cfg = self.node_configs.get(node_type, {})
        body = {
            "acceleratorType": node_cfg.get(
                "accelerator_type", self.accelerator_type),
            "runtimeVersion": node_cfg.get(
                "runtime_version", self.runtime_version),
            "labels": {"ray-tpu-cluster": self.cluster_name,
                       "ray-tpu-node-type": node_type or "worker",
                       **{k.replace("_", "-").lower(): str(v).lower()
                          for k, v in labels.items()}},
            "metadata": {"ray-tpu-resources": json.dumps(resources)},
        }
        self._call("POST", f"nodes?nodeId={node_id}", body)
        self._label_cache[node_id] = dict(body["labels"])
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._call("DELETE", f"nodes/{node_id}")
        self._label_cache.pop(node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        payload = self._call("GET", "nodes")
        out = []
        for node in payload.get("nodes", []):
            labels = node.get("labels", {})
            if labels.get("ray-tpu-cluster") != self.cluster_name:
                continue
            if node.get("state") in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            node_id = node["name"].rsplit("/", 1)[-1]
            self._label_cache[node_id] = labels
            out.append(node_id)
        return out

    def node_type_of(self, node_id: str) -> str:
        labels = self._label_cache.get(node_id)
        if labels is None:  # not seen by a list yet — one REST GET
            try:
                node = self._call("GET", f"nodes/{node_id}")
            except RuntimeError:
                return ""
            labels = node.get("labels", {})
            self._label_cache[node_id] = labels
        return labels.get("ray-tpu-node-type", "")

    def node_ip(self, node_id: str) -> Optional[str]:
        try:
            node = self._call("GET", f"nodes/{node_id}")
        except RuntimeError:
            return None
        eps = node.get("networkEndpoints", [])
        return eps[0].get("ipAddress") if eps else None

    def wait_ready(self, node_id: str, timeout_s: float = 600.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            node = self._call("GET", f"nodes/{node_id}")
            if node.get("state") == "READY":
                return True
            time.sleep(self.poll_interval_s)
        return False


class SSHCommandRunner:
    """Runs setup/start commands on a launched node over ssh
    (reference: autoscaler/_private/command_runner.py SSHCommandRunner).
    """

    def __init__(self, ip: str, *, user: str = "root",
                 key_path: Optional[str] = None,
                 connect_timeout_s: int = 10):
        self.ip = ip
        self.user = user
        self.key_path = key_path
        self.connect_timeout_s = connect_timeout_s

    def _ssh_base(self) -> List[str]:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", f"ConnectTimeout={self.connect_timeout_s}",
               "-o", "UserKnownHostsFile=/dev/null",
               "-o", "LogLevel=ERROR"]
        if self.key_path:
            cmd += ["-i", self.key_path]
        cmd.append(f"{self.user}@{self.ip}")
        return cmd

    def remote_command(self, cmd: str) -> List[str]:
        """The argv that would run `cmd` remotely (testable without a
        live host)."""
        return self._ssh_base() + [f"bash -lc {json.dumps(cmd)}"]

    def run(self, cmd: str, *, timeout_s: float = 300.0) -> str:
        out = subprocess.run(
            self.remote_command(cmd), capture_output=True, text=True,
            timeout=timeout_s)
        if out.returncode != 0:
            raise RuntimeError(
                f"remote command failed ({out.returncode}): "
                f"{out.stderr.strip()}")
        return out.stdout

    def rsync_up(self, local: str, remote: str) -> List[str]:
        ssh = " ".join(self._ssh_base()[:-1])
        return ["rsync", "-az", "-e", ssh, local,
                f"{self.user}@{self.ip}:{remote}"]


class KubeTpuNodeProvider(NodeProvider):
    """KubeRay/GKE-shaped provider (reference:
    autoscaler/_private/kuberay/node_provider.py): scaling is
    DECLARATIVE against a RayCluster-style custom resource — the
    provider PATCHes ``workerGroupSpecs[*].replicas`` (and
    ``scaleStrategy.workersToDelete`` for targeted scale-down) through
    the Kubernetes API and an operator reconciles the pods; node
    identity comes back from pod listings by label selector. This is
    how real TPU pods are provisioned on GKE (node pools == worker
    groups with a TPU accelerator/topology per group).

    Same injectable-transport design as GceTpuNodeProvider: the
    Kubernetes API server is a `transport` callable, so the provider
    is fully testable against an in-memory operator fake.
    """

    GROUP_LABEL = "ray.io/group"
    CLUSTER_LABEL = "ray.io/cluster"
    TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"

    def __init__(self, cluster_name: str, *,
                 namespace: str = "default",
                 api_server: str = "https://kubernetes.default.svc",
                 crd_group: str = "ray.io", crd_version: str = "v1",
                 default_group: str = "workers",
                 token: Optional[str] = None,
                 transport: Optional[Transport] = None,
                 poll_interval_s: float = 1.0):
        self.cluster_name = cluster_name
        self.namespace = namespace
        self.api_server = api_server.rstrip("/")
        self.crd_group = crd_group
        self.crd_version = crd_version
        self.default_group = default_group
        self.poll_interval_s = poll_interval_s
        self._transport = transport or _default_transport
        self._token = token
        self._label_cache: Dict[str, Dict[str, str]] = {}
        self._ip_cache: Dict[str, str] = {}
        self._phase_cache: Dict[str, str] = {}
        # Pending create handles → group, and handle → pod aliases
        # once the operator materializes them.
        self._pending: Dict[str, str] = {}
        self._pending_ts: Dict[str, float] = {}
        self._alias: Dict[str, str] = {}
        self._pending_seq = 0
        # A pending handle the operator never materializes (quota
        # exhausted, bad group config) must not count as provisioning
        # capacity forever: after this grace it is dropped and its
        # replica bump rolled back so the autoscaler can retry.
        self.pending_timeout_s = 900.0

    # -- Kubernetes API plumbing ---------------------------------------

    def _headers(self) -> Dict[str, str]:
        if self._token is None:
            try:
                with open(self.TOKEN_PATH) as f:
                    self._token = f.read().strip()
            except OSError:
                self._token = ""  # out-of-cluster kubeconfig proxies
        hdrs = {"Content-Type": "application/json"}
        if self._token:
            hdrs["Authorization"] = f"Bearer {self._token}"
        return hdrs

    def _call(self, method: str, path: str, body=None, *,
              content_type: Optional[str] = None,
              conflict_ok: bool = False) -> Optional[dict]:
        hdrs = self._headers()
        if content_type:
            hdrs["Content-Type"] = content_type
        status, payload = self._transport(
            method, f"{self.api_server}{path}", body, hdrs)
        if conflict_ok and status == 409:
            return None
        if status >= 300:
            raise RuntimeError(
                f"Kubernetes API {method} {path} failed "
                f"({status}): {payload}")
        return payload

    @property
    def _cr_path(self) -> str:
        return (f"/apis/{self.crd_group}/{self.crd_version}/namespaces/"
                f"{self.namespace}/rayclusters/{self.cluster_name}")

    def _get_cr(self) -> dict:
        return self._call("GET", self._cr_path)

    def _group_specs(self, cr: dict) -> List[dict]:
        return cr.setdefault("spec", {}).setdefault(
            "workerGroupSpecs", [])

    def _list_pods(self) -> List[dict]:
        sel = f"{self.CLUSTER_LABEL}={self.cluster_name}"
        out = self._call(
            "GET",
            f"/api/v1/namespaces/{self.namespace}/pods"
            f"?labelSelector={sel}")
        pods = out.get("items", [])
        self._label_cache = {}
        self._ip_cache = {}
        self._phase_cache = {}
        for p in pods:
            name = p["metadata"]["name"]
            self._label_cache[name] = p["metadata"].get("labels", {})
            ip = (p.get("status") or {}).get("podIP")
            if ip:
                self._ip_cache[name] = ip
            self._phase_cache[name] = (p.get("status") or {}).get(
                "phase", "Pending")
        return pods

    def _patch_group(self, group: str, mutate) -> None:
        """Index-targeted JSON Patch of ONE worker group's fields,
        guarded by a resourceVersion test op — a whole-array merge
        patch from a stale snapshot would clobber concurrent CR writers
        (the operator clearing workersToDelete, another client scaling
        a different group). `mutate(idx, spec)` returns the patch ops.
        Retries the read-modify-write on 409."""
        for _ in range(8):
            cr = self._get_cr()
            specs = self._group_specs(cr)
            idx = next((i for i, s in enumerate(specs)
                        if s.get("groupName") == group), None)
            if idx is None:
                raise ValueError(
                    f"RayCluster {self.cluster_name!r} has no worker "
                    f"group {group!r} (available: "
                    f"{[s.get('groupName') for s in specs]})")
            ops = list(mutate(idx, specs[idx]))
            rv = (cr.get("metadata") or {}).get("resourceVersion")
            if rv is not None:
                ops.insert(0, {"op": "test",
                               "path": "/metadata/resourceVersion",
                               "value": rv})
            if self._call("PATCH", self._cr_path, ops,
                          content_type="application/json-patch+json",
                          conflict_ok=True) is not None:
                return
            time.sleep(self.poll_interval_s)
        raise RuntimeError(
            f"CR patch for group {group!r} kept conflicting")

    # -- NodeProvider ---------------------------------------------------

    def create_node(self, resources: Dict[str, float],
                    labels: Dict[str, str],
                    node_type: str = "") -> str:
        """Non-blocking (the autoscaler's update loop calls this
        inline): bump the group's replicas and return a PENDING handle
        immediately; the handle resolves to the operator-created pod on
        any later listing (non_terminated_nodes / wait_ready /
        node_ip). A TPU node pool can take minutes to provision —
        polling here would stall all reconciliation."""
        group = node_type or self.default_group

        def bump(idx, spec):
            return [{"op": "replace" if "replicas" in spec else "add",
                     "path": f"/spec/workerGroupSpecs/{idx}/replicas",
                     "value": int(spec.get("replicas", 0)) + 1}]

        self._patch_group(group, bump)
        self._pending_seq += 1
        handle = f"pending-{group}-{self._pending_seq}"
        self._pending[handle] = group
        self._pending_ts[handle] = time.monotonic()
        return handle

    def _resolve_pending(self) -> None:
        """Match operator-created pods to outstanding pending handles
        (FIFO per group). Called after every pod listing."""
        claimed = set(self._alias.values())
        for handle in list(self._pending):
            group = self._pending[handle]
            for name, labels in self._label_cache.items():
                if name in claimed:
                    continue
                if labels.get(self.GROUP_LABEL) == group:
                    self._alias[handle] = name
                    claimed.add(name)
                    del self._pending[handle]
                    break

    def _refresh(self) -> None:
        self._list_pods()
        self._resolve_pending()

    def _real_id(self, node_id: str) -> Optional[str]:
        if node_id in self._alias:
            return self._alias[node_id]
        if node_id in self._pending:
            return None  # not materialized yet
        return node_id

    def _drop_pending(self, handle: str) -> None:
        """Undo a never-materialized handle's replica bump."""
        group = self._pending.pop(handle)
        self._pending_ts.pop(handle, None)
        self._patch_group(group, lambda idx, spec: [
            {"op": "replace",
             "path": f"/spec/workerGroupSpecs/{idx}/replicas",
             "value": max(0, int(spec.get("replicas", 0)) - 1)}])

    def terminate_node(self, node_id: str) -> None:
        self._refresh()
        if node_id in self._pending:
            self._drop_pending(node_id)
            return
        real = self._alias.pop(node_id, node_id)
        labels = self._label_cache.get(real)
        if labels is None:
            # Unknown pod (already deleted / stale id): guessing a
            # group here would scale down an unrelated pool.
            return
        group = labels.get(self.GROUP_LABEL, self.default_group)

        def down_and_name(idx, spec):
            strategy = spec.get("scaleStrategy") or {}
            to_delete = list(strategy.get("workersToDelete") or [])
            if real not in to_delete:
                to_delete.append(real)
            return [
                {"op": "replace",
                 "path": f"/spec/workerGroupSpecs/{idx}/replicas",
                 "value": max(0, int(spec.get("replicas", 0)) - 1)},
                {"op": "add",
                 "path": f"/spec/workerGroupSpecs/{idx}/scaleStrategy",
                 "value": dict(strategy,
                               workersToDelete=to_delete)},
            ]

        self._patch_group(group, down_and_name)

    def non_terminated_nodes(self) -> List[str]:
        self._refresh()
        alive_phase = ("Running", "Pending")
        aliased = set(self._alias.values())
        out = [name for name, phase in self._phase_cache.items()
               if phase in alive_phase and name not in aliased]
        # Resolved handles keep their original id for the autoscaler's
        # pending-launch bookkeeping — but only while their POD is
        # alive: a preempted/failed pod behind an alias would otherwise
        # count as capacity forever and never be replaced.
        for handle, real in list(self._alias.items()):
            if self._phase_cache.get(real) in alive_phase:
                out.append(handle)
            else:
                del self._alias[handle]
        # Unresolved handles count as provisioning capacity within the
        # grace window; stale ones are dropped (replica bump undone).
        now = time.monotonic()
        for handle in list(self._pending):
            if (now - self._pending_ts.get(handle, now)
                    > self.pending_timeout_s):
                self._drop_pending(handle)
            else:
                out.append(handle)
        return out

    def node_type_of(self, node_id: str) -> str:
        # Served from the caches the last listing filled — reconcile
        # loops call this once per node right after
        # non_terminated_nodes(); a list call per lookup would be N+1
        # API GETs per tick (the GCE provider caches for the same
        # reason).
        if node_id in self._pending:
            return self._pending[node_id]
        real = self._real_id(node_id)
        if real is not None and real not in self._label_cache:
            self._refresh()
            real = self._real_id(node_id)
        return self._label_cache.get(real or "", {}).get(
            self.GROUP_LABEL, "")

    def node_ip(self, node_id: str) -> Optional[str]:
        real = self._real_id(node_id)
        if real is None or real not in self._ip_cache:
            self._refresh()
            real = self._real_id(node_id)
        return self._ip_cache.get(real) if real else None

    def wait_ready(self, node_id: str, timeout_s: float = 600.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._refresh()
            real = self._real_id(node_id)
            if real and self._phase_cache.get(real) == "Running":
                return True
            time.sleep(self.poll_interval_s)
        return False


class OnPremNodeProvider(NodeProvider):
    """Fixed host inventory for bare-metal / reserved TPU pods
    (reference: autoscaler/_private/local/node_provider.py
    LocalNodeProvider over a configured worker_ips list, with
    ClusterState :33 — a lock-guarded json file recording which hosts
    are claimed, so concurrent monitors and restarts agree).

    "Scaling up" CLAIMS an idle host from the pool and runs the
    configured start command on it over ssh; scaling down runs the stop
    command and releases the claim. Hosts are dicts
    {"ip": ..., "type": ..., "labels": {...}} (or bare ip strings).
    The command executor is injectable for tests."""

    def __init__(self, hosts: List, *, cluster_name: str = "default",
                 state_path: Optional[str] = None,
                 start_command: Optional[str] = None,
                 stop_command: Optional[str] = None,
                 ssh_user: str = "root",
                 ssh_key_path: Optional[str] = None,
                 exec_fn=None):
        self.hosts: Dict[str, Dict] = {}
        for h in hosts:
            if isinstance(h, str):
                h = {"ip": h}
            ip = str(h["ip"])
            self.hosts[ip] = {"ip": ip,
                              "type": str(h.get("type", "")),
                              "labels": dict(h.get("labels") or {})}
        if not self.hosts:
            raise ValueError("on_prem provider needs a non-empty host list")
        self.cluster_name = cluster_name
        self.state_path = state_path or os.path.join(
            os.path.expanduser("~"), ".ray_tpu",
            f"onprem-{cluster_name}.json")
        state_dir = os.path.dirname(self.state_path)
        if state_dir:  # bare filename = cwd, nothing to create
            os.makedirs(state_dir, exist_ok=True)
        self.start_command = start_command
        self.stop_command = stop_command
        self._runner = lambda ip: SSHCommandRunner(
            ip, user=ssh_user, key_path=ssh_key_path)
        # exec_fn(ip, command) — defaults to ssh; injectable.
        self._exec = exec_fn or (
            lambda ip, cmd: self._runner(ip).run(cmd))

    # -- claim state (flock'd json: reference ClusterState) ------------
    def _with_state(self, mutate):
        import fcntl

        with open(self.state_path, "a+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            f.seek(0)
            raw = f.read()
            try:
                state = json.loads(raw) if raw.strip() else {}
            except ValueError:
                state = {}
            claims = state.setdefault("claims", {})
            out = mutate(claims)
            f.seek(0)
            f.truncate()
            json.dump(state, f)
        return out

    # -- NodeProvider ---------------------------------------------------
    def create_node(self, resources: Dict[str, float],
                    labels: Dict[str, str], node_type: str = "") -> str:
        def claim(claims):
            for ip, h in self.hosts.items():
                if ip in claims:
                    continue
                if node_type and h["type"] and h["type"] != node_type:
                    continue
                # Label-selector claiming: every requested label must be
                # present on the host (same semantics as the scheduler's
                # label constraints).
                if labels and any(h["labels"].get(k) != v
                                  for k, v in labels.items()):
                    continue
                claims[ip] = {"type": node_type or h["type"],
                              "ts": time.time()}
                return ip
            raise RuntimeError(
                f"on_prem pool exhausted: all {len(self.hosts)} hosts "
                f"claimed (or none matches type {node_type!r} / labels "
                f"{labels!r})")

        ip = self._with_state(claim)
        if self.start_command:
            try:
                self._exec(ip, self.start_command)
            except Exception:
                # Release the claim: a host whose start failed must not
                # leak out of the pool.
                self._with_state(lambda c: c.pop(ip, None))
                raise
        return ip

    def terminate_node(self, node_id: str) -> None:
        if self.stop_command:
            try:
                self._exec(node_id, self.stop_command)
            except Exception:  # noqa: BLE001 — host may be dead already
                pass
        self._with_state(lambda c: c.pop(node_id, None))

    def non_terminated_nodes(self) -> List[str]:
        return self._with_state(
            lambda c: [ip for ip in c if ip in self.hosts])

    def node_type_of(self, node_id: str) -> str:
        return self._with_state(
            lambda c: (c.get(node_id) or {}).get("type", "")) or \
            self.hosts.get(node_id, {}).get("type", "")

    def node_ip(self, node_id: str) -> Optional[str]:
        return node_id if node_id in self.hosts else None

    def wait_ready(self, node_id: str, timeout_s: float = 60.0) -> bool:
        return node_id in self.non_terminated_nodes()
