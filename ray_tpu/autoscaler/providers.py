"""Cloud node providers + command runners.

Capability-equivalent to the reference's cloud provider tree
(reference: autoscaler/_private/gcp/node_provider.py, aws/, command_runner.py
SSHCommandRunner). TPU-first: the flagship provider launches **TPU VM
pods** through the Cloud TPU REST API (tpu.googleapis.com) rather than
GPU instances through GCE; one "node" is a TPU host with its chips as
schedulable resources.

All HTTP goes through an injectable transport so the provider logic is
fully testable without credentials or egress (the reference tests its
providers the same way — mocked cloud APIs, autoscaler_test_utils).
"""

from __future__ import annotations

import json
import logging
import subprocess
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .autoscaler import NodeProvider

logger = logging.getLogger("ray_tpu")

# transport(method, url, body_dict_or_none, headers) -> (status, body_dict)
Transport = Callable[[str, str, Optional[dict], Dict[str, str]],
                     Tuple[int, dict]]

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")


def _default_transport(method: str, url: str, body: Optional[dict],
                       headers: Dict[str, str]) -> Tuple[int, dict]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **headers})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
            return resp.status, (json.loads(payload) if payload else {})
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            return e.code, json.loads(payload)
        except Exception:  # noqa: BLE001
            return e.code, {"error": payload.decode(errors="replace")}


def _metadata_token(transport: Transport) -> str:
    status, body = transport("GET", _METADATA_TOKEN_URL, None,
                             {"Metadata-Flavor": "Google"})
    if status != 200 or "access_token" not in body:
        raise RuntimeError(
            "no GCE service-account token available (not on GCE / no "
            "scopes); pass token= or transport= to GceTpuNodeProvider")
    return body["access_token"]


class GceTpuNodeProvider(NodeProvider):
    """Launches/terminates TPU VM nodes via the Cloud TPU v2 API.

    One autoscaler node == one TPU pod slice (`accelerator_type`, e.g.
    "v5litepod-8"); `cluster_name` labels every node so
    non_terminated_nodes only sees this cluster's machines.
    """

    def __init__(self, project: str, zone: str, cluster_name: str, *,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 node_configs: Optional[Dict[str, dict]] = None,
                 token: Optional[str] = None,
                 transport: Optional[Transport] = None,
                 poll_interval_s: float = 2.0):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        # Per-node-type launch params (accelerator_type/runtime_version
        # overrides) keyed by node type name — from the cluster YAML's
        # available_node_types[*].node_config.
        self.node_configs = dict(node_configs or {})
        self.poll_interval_s = poll_interval_s
        self._transport = transport or _default_transport
        self._token = token
        self._counter = 0
        # node_id -> labels, filled by the list call so per-node lookups
        # (node_type_of) don't each cost a REST GET.
        self._label_cache: Dict[str, Dict[str, str]] = {}

    # -- REST plumbing -------------------------------------------------

    @property
    def _base(self) -> str:
        return (f"https://tpu.googleapis.com/v2/projects/{self.project}"
                f"/locations/{self.zone}")

    def _headers(self) -> Dict[str, str]:
        if self._token is None:
            self._token = _metadata_token(self._transport)
        return {"Authorization": f"Bearer {self._token}"}

    def _call(self, method: str, path: str,
              body: Optional[dict] = None) -> dict:
        status, payload = self._transport(
            method, f"{self._base}/{path}", body, self._headers())
        if status == 401:  # token expired: refresh once
            self._token = None
            status, payload = self._transport(
                method, f"{self._base}/{path}", body, self._headers())
        if status >= 300:
            raise RuntimeError(
                f"TPU API {method} {path} failed ({status}): {payload}")
        return payload

    # -- NodeProvider --------------------------------------------------

    def create_node(self, resources: Dict[str, float],
                    labels: Dict[str, str],
                    node_type: str = "") -> str:
        self._counter += 1
        node_id = f"{self.cluster_name}-w{self._counter}-{int(time.time())}"
        node_cfg = self.node_configs.get(node_type, {})
        body = {
            "acceleratorType": node_cfg.get(
                "accelerator_type", self.accelerator_type),
            "runtimeVersion": node_cfg.get(
                "runtime_version", self.runtime_version),
            "labels": {"ray-tpu-cluster": self.cluster_name,
                       "ray-tpu-node-type": node_type or "worker",
                       **{k.replace("_", "-").lower(): str(v).lower()
                          for k, v in labels.items()}},
            "metadata": {"ray-tpu-resources": json.dumps(resources)},
        }
        self._call("POST", f"nodes?nodeId={node_id}", body)
        self._label_cache[node_id] = dict(body["labels"])
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._call("DELETE", f"nodes/{node_id}")
        self._label_cache.pop(node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        payload = self._call("GET", "nodes")
        out = []
        for node in payload.get("nodes", []):
            labels = node.get("labels", {})
            if labels.get("ray-tpu-cluster") != self.cluster_name:
                continue
            if node.get("state") in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            node_id = node["name"].rsplit("/", 1)[-1]
            self._label_cache[node_id] = labels
            out.append(node_id)
        return out

    def node_type_of(self, node_id: str) -> str:
        labels = self._label_cache.get(node_id)
        if labels is None:  # not seen by a list yet — one REST GET
            try:
                node = self._call("GET", f"nodes/{node_id}")
            except RuntimeError:
                return ""
            labels = node.get("labels", {})
            self._label_cache[node_id] = labels
        return labels.get("ray-tpu-node-type", "")

    def node_ip(self, node_id: str) -> Optional[str]:
        try:
            node = self._call("GET", f"nodes/{node_id}")
        except RuntimeError:
            return None
        eps = node.get("networkEndpoints", [])
        return eps[0].get("ipAddress") if eps else None

    def wait_ready(self, node_id: str, timeout_s: float = 600.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            node = self._call("GET", f"nodes/{node_id}")
            if node.get("state") == "READY":
                return True
            time.sleep(self.poll_interval_s)
        return False


class SSHCommandRunner:
    """Runs setup/start commands on a launched node over ssh
    (reference: autoscaler/_private/command_runner.py SSHCommandRunner).
    """

    def __init__(self, ip: str, *, user: str = "root",
                 key_path: Optional[str] = None,
                 connect_timeout_s: int = 10):
        self.ip = ip
        self.user = user
        self.key_path = key_path
        self.connect_timeout_s = connect_timeout_s

    def _ssh_base(self) -> List[str]:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", f"ConnectTimeout={self.connect_timeout_s}",
               "-o", "UserKnownHostsFile=/dev/null",
               "-o", "LogLevel=ERROR"]
        if self.key_path:
            cmd += ["-i", self.key_path]
        cmd.append(f"{self.user}@{self.ip}")
        return cmd

    def remote_command(self, cmd: str) -> List[str]:
        """The argv that would run `cmd` remotely (testable without a
        live host)."""
        return self._ssh_base() + [f"bash -lc {json.dumps(cmd)}"]

    def run(self, cmd: str, *, timeout_s: float = 300.0) -> str:
        out = subprocess.run(
            self.remote_command(cmd), capture_output=True, text=True,
            timeout=timeout_s)
        if out.returncode != 0:
            raise RuntimeError(
                f"remote command failed ({out.returncode}): "
                f"{out.stderr.strip()}")
        return out.stdout

    def rsync_up(self, local: str, remote: str) -> List[str]:
        ssh = " ".join(self._ssh_base()[:-1])
        return ["rsync", "-az", "-e", ssh, local,
                f"{self.user}@{self.ip}:{remote}"]
