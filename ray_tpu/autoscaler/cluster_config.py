"""Cluster YAML config + up/down launcher.

Capability-equivalent to the reference's `ray up/down cluster.yaml`
(reference: scripts/scripts.py up :1276 / down :1352, schema
autoscaler/ray-schema.json). TPU-first differences: the "head" is the
process running `up` (TPU pods are reached from a controller host, not
ssh'd into to become a head), and worker node types are TPU slices.

YAML shape:

    cluster_name: demo
    max_workers: 8
    idle_timeout_minutes: 5
    provider:
      type: gce_tpu            # gce_tpu | kuberay | on_prem | local | mock
      project: my-project
      zone: us-central2-b
    auth:
      ssh_user: root
      ssh_private_key: ~/.ssh/id_rsa
    available_node_types:
      tpu_v5e_8:
        resources: {TPU: 8, CPU: 96}
        min_workers: 0
        max_workers: 4
        node_config:
          accelerator_type: v5litepod-8
          runtime_version: v2-alpha-tpuv5-lite
    setup_commands:
      - pip install -e .
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .autoscaler import (
    AutoscalerConfig,
    LocalNodeProvider,
    MockProvider,
    Monitor,
    NodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
)

logger = logging.getLogger("ray_tpu")


@dataclass
class ClusterConfig:
    cluster_name: str
    provider: Dict[str, object]
    max_workers: int = 8
    idle_timeout_minutes: float = 5.0
    auth: Dict[str, str] = field(default_factory=dict)
    available_node_types: Dict[str, NodeTypeConfig] = field(
        default_factory=dict)
    setup_commands: List[str] = field(default_factory=list)

    @classmethod
    def from_yaml(cls, path: str) -> "ClusterConfig":
        import yaml  # lazy: PyYAML isn't a package dependency

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "ClusterConfig":
        if "cluster_name" not in raw:
            raise ValueError("cluster config needs cluster_name")
        if "provider" not in raw or "type" not in raw["provider"]:
            raise ValueError("cluster config needs provider.type")
        types = {}
        for name, spec in (raw.get("available_node_types") or {}).items():
            if "resources" not in spec:
                raise ValueError(f"node type {name!r} needs resources")
            types[name] = NodeTypeConfig(
                resources={k: float(v)
                           for k, v in spec["resources"].items()},
                min_workers=int(spec.get("min_workers", 0)),
                max_workers=int(spec.get("max_workers",
                                         raw.get("max_workers", 8))),
                labels=dict(spec.get("labels", {})),
                node_config=dict(spec.get("node_config", {})),
            )
        return cls(
            cluster_name=raw["cluster_name"],
            provider=dict(raw["provider"]),
            max_workers=int(raw.get("max_workers", 8)),
            idle_timeout_minutes=float(raw.get("idle_timeout_minutes", 5)),
            auth=dict(raw.get("auth", {})),
            available_node_types=types,
            setup_commands=list(raw.get("setup_commands", [])),
        )


def make_provider(cfg: ClusterConfig, **overrides) -> NodeProvider:
    ptype = cfg.provider["type"]
    if ptype == "mock":
        return MockProvider()
    if ptype == "local":
        return LocalNodeProvider()
    if ptype == "gce_tpu":
        from .providers import GceTpuNodeProvider

        kw = {k: v for k, v in cfg.provider.items()
              if k in ("accelerator_type", "runtime_version")}
        kw.setdefault("node_configs", {
            name: tc.node_config
            for name, tc in cfg.available_node_types.items()})
        kw.update(overrides)
        return GceTpuNodeProvider(
            project=str(cfg.provider["project"]),
            zone=str(cfg.provider["zone"]),
            cluster_name=cfg.cluster_name, **kw)
    if ptype == "kuberay":
        from .providers import KubeTpuNodeProvider

        kw = {k: v for k, v in cfg.provider.items()
              if k in ("namespace", "api_server", "crd_group",
                       "crd_version", "default_group")}
        kw.update(overrides)
        return KubeTpuNodeProvider(cluster_name=cfg.cluster_name, **kw)
    if ptype == "on_prem":
        from .providers import OnPremNodeProvider

        kw = {k: v for k, v in cfg.provider.items()
              if k in ("state_path", "start_command", "stop_command",
                       "ssh_user", "ssh_key_path")}
        kw.update(overrides)
        return OnPremNodeProvider(
            list(cfg.provider.get("hosts") or []),
            cluster_name=cfg.cluster_name, **kw)
    raise ValueError(f"unknown provider type {ptype!r}")


class ClusterLauncher:
    """`up` = ensure per-type min_workers and run the autoscaler monitor
    in this process; `down` = terminate every provider node."""

    def __init__(self, cfg: ClusterConfig,
                 provider: Optional[NodeProvider] = None,
                 runner_factory=None):
        self.cfg = cfg
        self.provider = provider or make_provider(cfg)
        self.autoscaler: Optional[StandardAutoscaler] = None
        self.monitor: Optional[Monitor] = None
        # runner_factory(ip) -> object with .run(cmd); injectable so
        # setup is testable without ssh targets.
        self._runner_factory = runner_factory or self._default_runner
        self._provisioned: set = set()

    def _default_runner(self, ip: str):
        from .providers import SSHCommandRunner

        return SSHCommandRunner(
            ip, user=self.cfg.auth.get("ssh_user", "root"),
            key_path=self.cfg.auth.get("ssh_private_key"))

    def _setup_node(self, node_id: str) -> bool:
        """Wait for the node and run setup_commands over ssh (providers
        without wait_ready/node_ip — mock/local — skip silently).
        Idempotent per node; raises on command failure (the Monitor-path
        wrapper logs instead, autoscaler.py _launched)."""
        if not self.cfg.setup_commands or node_id in self._provisioned:
            return True
        wait = getattr(self.provider, "wait_ready", None)
        get_ip = getattr(self.provider, "node_ip", None)
        if wait is None or get_ip is None:
            return True
        if not wait(node_id):
            raise RuntimeError(f"node {node_id} never became ready")
        ip = get_ip(node_id)
        if not ip:
            raise RuntimeError(f"node {node_id} has no reachable IP")
        runner = self._runner_factory(ip)
        for cmd in self.cfg.setup_commands:
            # Template vars so a setup command can register the daemon
            # under the PROVIDER's node id ("ray-tpu start
            # --address=... --node-id={node_id}") — that id match is
            # what lets the autoscaler stop counting the node as
            # pending-launch capacity once it joins the scheduler.
            cmd = cmd.replace("{node_id}", node_id)
            runner.run(cmd)
        self._provisioned.add(node_id)
        return True

    def up(self, *, start_monitor: bool = True,
           monitor_interval_s: float = 5.0) -> Dict[str, int]:
        as_cfg = AutoscalerConfig(
            max_workers=self.cfg.max_workers,
            idle_timeout_s=self.cfg.idle_timeout_minutes * 60.0,
            node_types=dict(self.cfg.available_node_types),
        )
        # Provisioning rides the autoscaler's launch hook so nodes the
        # Monitor adds later get setup_commands too (failures there are
        # logged, not raised — there is no caller to raise to).
        self.autoscaler = StandardAutoscaler(
            as_cfg, self.provider, on_node_launched=self._setup_node)
        result = self.autoscaler.update()  # satisfies min_workers floors
        # Synchronous pass over EVERY live node — pre-existing nodes
        # (launcher restart, changed setup_commands) get provisioned,
        # and failures here propagate to the up() caller. Idempotent:
        # nodes the hook already set up are skipped.
        for node_id in self.provider.non_terminated_nodes():
            self._setup_node(node_id)
        if start_monitor:
            self.monitor = Monitor(self.autoscaler,
                                   interval_s=monitor_interval_s).start()
        logger.info("cluster %s up: %s", self.cfg.cluster_name, result)
        return result

    def down(self) -> int:
        if self.monitor is not None:
            self.monitor.stop()
            self.monitor = None
        n = 0
        for node_id in self.provider.non_terminated_nodes():
            self.provider.terminate_node(node_id)
            n += 1
        logger.info("cluster %s down: terminated %d nodes",
                    self.cfg.cluster_name, n)
        return n
