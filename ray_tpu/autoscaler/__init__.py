from .autoscaler import (
    AutoscalerConfig,
    LocalNodeProvider,
    MockProvider,
    Monitor,
    NodeProvider,
    StandardAutoscaler,
)

__all__ = ["AutoscalerConfig", "NodeProvider", "LocalNodeProvider",
           "MockProvider", "StandardAutoscaler", "Monitor"]
