from .autoscaler import (
    AutoscalerConfig,
    LocalNodeProvider,
    MockProvider,
    Monitor,
    NodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
)
from .cluster_config import ClusterConfig, ClusterLauncher, make_provider

__all__ = ["AutoscalerConfig", "NodeTypeConfig", "NodeProvider",
           "LocalNodeProvider", "MockProvider", "StandardAutoscaler",
           "Monitor", "ClusterConfig", "ClusterLauncher", "make_provider"]
