"""Demand-based autoscaler.

Capability-equivalent to the reference's autoscaler v1 core loop
(reference: autoscaler/_private/autoscaler.py StandardAutoscaler :171 —
reads pending/infeasible resource demand from GCS, bin-packs it into
node types via resource_demand_scheduler.py, launches through a
NodeProvider ABC (node_provider.py:13), terminates idle nodes after
idle_timeout; driven by the head-side Monitor loop, monitor.py:126).
Tested against a mock provider exactly like the reference
(python/ray/tests/autoscaler_test_utils.py MockProvider).

TPU-native specifics: a worker "node" is a whole TPU host (slice
member); `worker_resources` therefore usually carries {"TPU": n} and
slice labels so SliceAffinity gang placement can target new capacity.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.resources import ResourceSet
from ..core.scheduler import NodeState
from ..observability import get_recorder

logger = logging.getLogger("ray_tpu")


@dataclass
class NodeTypeConfig:
    """One launchable worker shape (reference: available_node_types in
    the cluster YAML, autoscaler/ray-schema.json)."""

    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 8
    labels: Dict[str, str] = field(default_factory=dict)
    # Provider-specific launch parameters (e.g. GCE TPU accelerator_type,
    # runtime_version) — passed through to the provider opaquely.
    node_config: Dict[str, object] = field(default_factory=dict)


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 8
    # Resources each launched worker node provides.
    worker_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})
    idle_timeout_s: float = 60.0
    # Upper bound on nodes launched per update (relative to current size,
    # reference: upscaling_speed).
    upscaling_speed: float = 1.0
    worker_labels: Dict[str, str] = field(default_factory=dict)
    # How long a launched-but-not-joined node's capacity is credited
    # against demand before it is presumed failed and relaunchable
    # (GCE TPU pods take minutes to boot + join).
    launch_grace_s: float = 600.0
    # Multi-shape mode: when set, demand is packed per node type and
    # worker_resources/worker_labels are ignored.
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)


class NodeProvider:
    """Provider ABC (reference: autoscaler/node_provider.py:13)."""

    def create_node(self, resources: Dict[str, float],
                    labels: Dict[str, str],
                    node_type: str = "") -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, node_id: str) -> str:
        """Node type a node was launched as ('' if untyped)."""
        return ""


class LocalNodeProvider(NodeProvider):
    """Adds real schedulable nodes to the running runtime (the
    in-process analog of launching a VM; reference local provider
    autoscaler/_private/local/)."""

    def __init__(self, runtime=None):
        from ..core import runtime as _rt

        self._rt = runtime or _rt.global_runtime()
        self._nodes: List[str] = []
        self._types: Dict[str, str] = {}

    def create_node(self, resources, labels, node_type: str = "") -> str:
        node_id = f"as-worker-{uuid.uuid4().hex[:8]}"
        node = NodeState(node_id, ResourceSet(resources),
                         max_workers=max(1, int(resources.get("CPU", 1))))
        node.labels.update(labels)
        self._rt.scheduler.add_node(node)
        self._nodes.append(node_id)
        self._types[node_id] = node_type
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._rt.scheduler.remove_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_type_of(self, node_id: str) -> str:
        return self._types.get(node_id, "")


class MockProvider(NodeProvider):
    """Records create/terminate calls (reference:
    autoscaler_test_utils.MockProvider) — no cluster mutation."""

    def __init__(self):
        self.created: List[Dict] = []
        self.terminated: List[str] = []
        self._alive: List[str] = []

    def create_node(self, resources, labels, node_type: str = "") -> str:
        node_id = f"mock-{len(self.created)}"
        self.created.append({"node_id": node_id, "resources": resources,
                             "labels": labels, "node_type": node_type})
        self._alive.append(node_id)
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self.terminated.append(node_id)
        if node_id in self._alive:
            self._alive.remove(node_id)

    def non_terminated_nodes(self) -> List[str]:
        return list(self._alive)

    def node_type_of(self, node_id: str) -> str:
        for c in self.created:
            if c["node_id"] == node_id:
                return c.get("node_type", "")
        return ""


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 runtime=None, on_node_launched=None):
        from ..core import runtime as _rt

        self.config = config
        self.provider = provider
        self._rt = runtime or _rt.global_runtime()
        self._idle_since: Dict[str, float] = {}
        # node_id -> launch time; capacity of these is credited against
        # demand until the node joins or the grace period lapses.
        self._pending_launch: Dict[str, float] = {}
        # Called with each new node_id right after create_node — the
        # cluster launcher hangs node provisioning (setup_commands)
        # here so Monitor-launched nodes get set up too.
        self._on_node_launched = on_node_launched

    def _launched(self, node_id: str) -> None:
        self._pending_launch[node_id] = time.monotonic()
        get_recorder().record("autoscaler", "node_launched",
                              node=node_id)
        if self._on_node_launched is not None:
            try:
                self._on_node_launched(node_id)
            except Exception:  # noqa: BLE001
                logger.exception("node %s provisioning failed", node_id)

    # -- sizing ------------------------------------------------------------
    @staticmethod
    def _selector_ok(selector: Dict[str, str],
                     labels: Dict[str, str]) -> bool:
        if not selector:
            return True
        return all(labels.get(k) == v for k, v in selector.items())

    def _type_labels(self, t: str) -> Dict[str, str]:
        tc = self.config.node_types[t]
        labels = dict(tc.labels)
        labels.setdefault("node-type", t)
        return labels

    def _pending_capacity(self) -> List[tuple]:
        """[(capacity, labels)] of nodes LAUNCHED but not yet joined
        (provider-alive but absent from the scheduler). Crediting these
        against unmet demand damps relaunch storms while slow nodes
        (GCE TPU pods take minutes) boot — the reference autoscaler
        tracks launching nodes the same way."""
        joined = {n.node_id for n in self._rt.scheduler.nodes()}
        now = time.monotonic()
        out = []
        for nid in self.provider.non_terminated_nodes():
            if nid in joined:
                # Joined under its provider id (setup_commands pass
                # --node-id={node_id}) — no longer pending.
                self._pending_launch.pop(nid, None)
                continue
            since = self._pending_launch.get(nid)
            if since is None or now - since > self.config.launch_grace_s:
                # Unknown to this autoscaler instance or stuck past the
                # grace window: don't credit phantom capacity forever.
                continue
            if self.config.node_types:
                t = self.provider.node_type_of(nid)
                tc = self.config.node_types.get(t)
                if tc is None:
                    continue
                out.append((ResourceSet(tc.resources),
                            self._type_labels(t)))
            else:
                labels = dict(self.config.worker_labels)
                out.append((ResourceSet(self.config.worker_resources),
                            labels))
        return out

    def _demand_nodes_needed(self) -> int:
        """Bin-pack pending demand into worker-node-sized bins
        (reference: resource_demand_scheduler.py get_nodes_for).

        Demand is first absorbed by the free capacity of nodes that
        already exist or are still launching (the reference packs onto
        existing nodes' available resources before asking for new ones)
        — otherwise a transiently-queued task next to an idle worker
        launches a node, and slow-booting nodes relaunch every tick.
        Hard affinity / PG demand can't be satisfied by arbitrary free
        capacity — it always counts as unmet.
        """
        unmet = self._unmet_demand()
        cap = ResourceSet(self.config.worker_resources)
        worker_labels = dict(self.config.worker_labels)
        nodes_needed = 0
        remaining = None
        for req, selector in unmet:
            if not req.fits(cap):
                continue  # never satisfiable by this node type
            if not self._selector_ok(selector, worker_labels):
                continue  # no launchable node can match the selector
            if remaining is not None and req.fits(remaining):
                remaining = remaining.subtract(req)
                continue
            nodes_needed += 1
            remaining = cap.subtract(req)
        return nodes_needed

    def _unmet_demand(self) -> List[tuple]:
        """[(request, label_selector)] not coverable by existing free
        or pending-launch capacity (label-matched)."""
        sched = self._rt.scheduler
        if hasattr(sched, "pending_demand_detailed"):
            demand = sched.pending_demand_detailed()
        else:
            demand = [(r, False, {}) for r in sched.pending_demand()]
        free = [(n.available, getattr(n, "labels", {}))
                for n in sched.nodes()]
        free += self._pending_capacity()
        unmet = []
        for req, hard, selector in sorted(
                demand, key=lambda rc: -sum(rc[0].to_dict().values())):
            if hard:
                unmet.append((req, selector))
                continue
            for i, (f, labels) in enumerate(free):
                if req.fits(f) and self._selector_ok(selector, labels):
                    free[i] = (f.subtract(req), labels)
                    break
            else:
                unmet.append((req, selector))
        return unmet

    def _demand_by_type(self, alive_by_type: Dict[str, int]
                        ) -> Dict[str, int]:
        """Pack unmet demand into node types (smallest type that fits
        each request first — reference: resource_demand_scheduler
        get_nodes_for / _utilization_scorer). A type at its max_workers
        stops opening bins; demand spills to the next-larger fitting
        type rather than hanging. Demand with a label_selector only
        opens bins of types whose labels satisfy the selector —
        launching a type that can never match would loop forever."""
        types = self.config.node_types
        # Smallest-first so a CPU task doesn't claim a TPU host.
        order = sorted(
            types, key=lambda t: sum(types[t].resources.values()))
        caps = {t: ResourceSet(types[t].resources) for t in types}
        needed: Dict[str, int] = {t: 0 for t in types}
        open_bins: List = []  # (type, remaining)
        for req, selector in self._unmet_demand():
            placed = False
            for i, (t, rem) in enumerate(open_bins):
                if req.fits(rem) and self._selector_ok(
                        selector, self._type_labels(t)):
                    open_bins[i] = (t, rem.subtract(req))
                    placed = True
                    break
            if placed:
                continue
            for t in order:
                launchable = (types[t].max_workers
                              - alive_by_type.get(t, 0) - needed[t])
                if (launchable > 0 and req.fits(caps[t])
                        and self._selector_ok(selector,
                                              self._type_labels(t))):
                    needed[t] += 1
                    open_bins.append((t, caps[t].subtract(req)))
                    break
        return needed

    def _update_multi_type(self) -> Dict[str, int]:
        """Reconciliation when node_types are configured: per-type
        min/max + demand packing, global max_workers cap."""
        types = self.config.node_types
        alive = self.provider.non_terminated_nodes()
        by_type: Dict[str, List[str]] = {t: [] for t in types}
        for nid in alive:
            t = self.provider.node_type_of(nid)
            if t in by_type:
                by_type[t].append(nid)
        launched = terminated = 0
        needed = self._demand_by_type(
            {t: len(ids) for t, ids in by_type.items()})
        total = len(alive)
        for t, tc in types.items():
            cur = len(by_type[t])
            target = max(cur + needed.get(t, 0), tc.min_workers)
            target = min(target, tc.max_workers)
            while (cur < target and total < self.config.max_workers):
                labels = dict(tc.labels)
                labels.setdefault("node-type", t)
                nid = self.provider.create_node(dict(tc.resources),
                                                labels, t)
                self._launched(nid)
                launched += 1
                cur += 1
                total += 1

        # Scale down per type, respecting per-type min_workers.
        now = time.monotonic()
        demand = self._rt.scheduler.pending_demand()
        by_id = {n.node_id: n for n in self._rt.scheduler.nodes()}
        for t, tc in types.items():
            n_alive = len(by_type[t])
            term_t = 0
            for node_id in by_type[t]:
                node = by_id.get(node_id)
                busy = node is not None and (
                    node.total.to_dict() != node.available.to_dict())
                if busy or demand:
                    self._idle_since.pop(node_id, None)
                    continue
                since = self._idle_since.setdefault(node_id, now)
                if (now - since >= self.config.idle_timeout_s
                        and n_alive - term_t > tc.min_workers):
                    self.provider.terminate_node(node_id)
                    get_recorder().record(
                        "autoscaler", "node_terminated", node=node_id,
                        node_type=t, reason="idle")
                    self._idle_since.pop(node_id, None)
                    terminated += 1
                    term_t += 1
        return {"launched": launched, "terminated": terminated}

    def update(self) -> Dict[str, int]:
        """One reconciliation step; returns {'launched': n,
        'terminated': m}."""
        if self.config.node_types:
            return self._update_multi_type()
        alive = self.provider.non_terminated_nodes()
        launched = terminated = 0

        # Scale up: demand + min_workers floor.
        needed = self._demand_nodes_needed()
        target = max(len(alive) + needed, self.config.min_workers)
        target = min(target, self.config.max_workers)
        headroom = max(1, int(self.config.upscaling_speed
                              * max(1, len(alive))))
        to_launch = min(target - len(alive), headroom)
        for _ in range(max(0, to_launch)):
            nid = self.provider.create_node(
                dict(self.config.worker_resources),
                dict(self.config.worker_labels))
            self._launched(nid)
            launched += 1

        # Scale down: fully idle beyond the timeout, above min_workers.
        now = time.monotonic()
        demand = self._rt.scheduler.pending_demand()
        by_id = {n.node_id: n for n in self._rt.scheduler.nodes()}
        candidates = self.provider.non_terminated_nodes()
        n_alive = len(candidates)
        for node_id in candidates:
            node = by_id.get(node_id)
            busy = node is not None and (
                node.total.to_dict() != node.available.to_dict())
            if busy or demand:
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if (now - since >= self.config.idle_timeout_s
                    and n_alive - terminated > self.config.min_workers):
                self.provider.terminate_node(node_id)
                get_recorder().record(
                    "autoscaler", "node_terminated", node=node_id,
                    reason="idle")
                self._idle_since.pop(node_id, None)
                terminated += 1
        return {"launched": launched, "terminated": terminated}


class Monitor:
    """The head-side loop driving the autoscaler
    (reference: autoscaler/_private/monitor.py:126)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Monitor":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.autoscaler.update()
                except Exception:  # noqa: BLE001
                    logger.exception("autoscaler update failed")

        self._thread = threading.Thread(
            target=loop, daemon=True, name="autoscaler-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
