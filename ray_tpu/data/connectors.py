"""External-system connectors on the Datasource/Datasink ABCs.

Breadth-parity with the reference's datasource library (reference:
python/ray/data/datasource/ — mongo_datasource.py,
bigquery_datasource.py, iceberg (read_iceberg), delta-style tables,
clickhouse, snowflake, avro, lance; 38 files): each connector plans
partitioned ReadTasks the streaming executor runs as ordinary tasks.

Design differences from the reference, deliberate:
- every connector takes an injectable `client_factory` so the
  partition-planning logic is exercised without the vendor package
  (the reference mocks at the package level in its tests);
- vendor packages are GATED, not vendored: the factory default raises
  an actionable ImportError when the package is absent (this image
  ships none of them — SURVEY.md environment constraints).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .dataset import Dataset
from .datasource import Datasink, Datasource, ReadTask, read_datasource


def _rows(block: Any) -> List[Dict[str, Any]]:
    from .block import BlockAccessor

    return list(BlockAccessor.for_block(block).iter_rows())


def _require(pkg: str, feature: str):
    try:
        return __import__(pkg)
    except ImportError as e:
        raise ImportError(
            f"{feature} requires the '{pkg}' package, which is not "
            f"installed. Pass client_factory=... to use an existing "
            f"client/connection instead.") from e


# ---------------------------------------------------------------------------
# MongoDB (reference: data/datasource/mongo_datasource.py)
# ---------------------------------------------------------------------------

class MongoDatasource(Datasource):
    """Partitions the PIPELINE OUTPUT into skip/limit windows over a
    stable `$sort` (partitioning without a total order would let
    separate executions hand different rows to different windows).
    The row count is taken through the pipeline too, so expanding
    stages ($unwind) and filters partition correctly. Each ReadTask
    opens its own client (serializable plan, one connection/task)."""

    def __init__(self, uri: str, database: str, collection: str, *,
                 pipeline: Optional[List[Dict]] = None,
                 sort_field: str = "_id",
                 client_factory: Optional[Callable[[], Any]] = None):
        self.uri = uri
        self.database = database
        self.collection = collection
        self.pipeline = pipeline or []
        self.sort_field = sort_field
        self.client_factory = client_factory or (
            lambda: _require("pymongo", "read_mongo").MongoClient(uri))

    def get_name(self) -> str:
        return f"mongo({self.database}.{self.collection})"

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        client = self.client_factory()
        coll = client[self.database][self.collection]
        counted = list(coll.aggregate(
            list(self.pipeline) + [{"$count": "n"}]))
        total = int(counted[0]["n"]) if counted else 0
        n = max(1, min(parallelism, total) if total else 1)
        per = (total + n - 1) // n if total else 0
        order = ([{"$sort": {self.sort_field: 1}}]
                 if self.sort_field else [])

        def make(skip: int, limit: int):
            def read():
                c = self.client_factory()
                cl = c[self.database][self.collection]
                stages = list(self.pipeline) + order + [
                    {"$skip": skip}, {"$limit": limit}]
                return list(cl.aggregate(stages))
            return read

        return [ReadTask(make(i * per, per)) for i in range(n)
                if total] or [ReadTask(lambda: [])]


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[Dict]] = None,
               sort_field: str = "_id", parallelism: int = 8,
               client_factory: Optional[Callable[[], Any]] = None
               ) -> Dataset:
    return read_datasource(
        MongoDatasource(uri, database, collection, pipeline=pipeline,
                        sort_field=sort_field,
                        client_factory=client_factory),
        parallelism=parallelism)


class MongoDatasink(Datasink):
    def __init__(self, uri: str, database: str, collection: str, *,
                 client_factory: Optional[Callable[[], Any]] = None):
        self.uri = uri
        self.database = database
        self.collection = collection
        self.client_factory = client_factory or (
            lambda: _require("pymongo", "write_mongo").MongoClient(uri))

    def write(self, block: Any) -> Any:
        rows = _rows(block)
        if rows:
            c = self.client_factory()
            c[self.database][self.collection].insert_many(rows)
        return len(rows)


def write_mongo(ds: Dataset, uri: str, database: str, collection: str,
                *, client_factory=None) -> List[Any]:
    from .datasource import write_datasink

    return write_datasink(ds, MongoDatasink(
        uri, database, collection, client_factory=client_factory))


# ---------------------------------------------------------------------------
# BigQuery (reference: data/datasource/bigquery_datasource.py)
# ---------------------------------------------------------------------------

class BigQueryDatasource(Datasource):
    """Row-range partitions over a table or query result.

    LIMIT/OFFSET partitioning is only sound over a TOTAL ORDER — the
    engine documents result order as undefined otherwise, so separate
    per-partition query executions could overlap or miss rows. With no
    `order_by` the read degrades to ONE task (correct, unpartitioned);
    pass order_by="<unique col>" to enable parallel partitions."""

    def __init__(self, project: str, dataset_table: Optional[str] = None,
                 *, query: Optional[str] = None,
                 order_by: Optional[str] = None,
                 client_factory: Optional[Callable[[], Any]] = None):
        if (dataset_table is None) == (query is None):
            raise ValueError(
                "exactly one of dataset_table / query is required")
        self.project = project
        self.dataset_table = dataset_table
        self.query = query
        self.order_by = order_by
        self.client_factory = client_factory or (
            lambda: _require(
                "google.cloud.bigquery", "read_bigquery"
            ).Client(project=project))

    def get_name(self) -> str:
        return f"bigquery({self.dataset_table or 'query'})"

    def _base_query(self) -> str:
        return self.query or f"SELECT * FROM `{self.dataset_table}`"

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if self.order_by is None:
            def read_all():
                c = self.client_factory()
                return [dict(r)
                        for r in c.query(self._base_query()).result()]

            return [ReadTask(read_all)]
        client = self.client_factory()
        count_q = (f"SELECT COUNT(*) AS n FROM "
                   f"({self._base_query()})")
        total = int(next(iter(client.query(count_q).result()))["n"])
        n = max(1, min(parallelism, total) if total else 1)
        per = (total + n - 1) // n if total else 0

        def make(offset: int, limit: int):
            def read():
                c = self.client_factory()
                q = (f"SELECT * FROM ({self._base_query()}) "
                     f"ORDER BY {self.order_by} "
                     f"LIMIT {limit} OFFSET {offset}")
                return [dict(r) for r in c.query(q).result()]
            return read

        return [ReadTask(make(i * per, per)) for i in range(n)
                if total] or [ReadTask(lambda: [])]


def read_bigquery(project: str, dataset_table: Optional[str] = None, *,
                  query: Optional[str] = None,
                  order_by: Optional[str] = None, parallelism: int = 8,
                  client_factory=None) -> Dataset:
    return read_datasource(
        BigQueryDatasource(project, dataset_table, query=query,
                           order_by=order_by,
                           client_factory=client_factory),
        parallelism=parallelism)


class BigQueryDatasink(Datasink):
    def __init__(self, project: str, dataset_table: str, *,
                 client_factory=None):
        self.project = project
        self.dataset_table = dataset_table
        self.client_factory = client_factory or (
            lambda: _require(
                "google.cloud.bigquery", "write_bigquery"
            ).Client(project=project))

    def write(self, block: Any) -> Any:
        rows = _rows(block)
        if rows:
            c = self.client_factory()
            c.load_table_from_json(rows, self.dataset_table).result()
        return len(rows)


def write_bigquery(ds: Dataset, project: str, dataset_table: str, *,
                   client_factory=None) -> List[Any]:
    from .datasource import write_datasink

    return write_datasink(ds, BigQueryDatasink(
        project, dataset_table, client_factory=client_factory))


# ---------------------------------------------------------------------------
# SQL writes (reference: data/datasource/sql_datasource.py write path).
# Works against any DBAPI2 connection — really testable with sqlite.
# ---------------------------------------------------------------------------

class SQLDatasink(Datasink):
    def __init__(self, table: str,
                 connection_factory: Callable[[], Any]):
        self.table = table
        self.connection_factory = connection_factory

    def write(self, block: Any) -> Any:
        rows = _rows(block)
        if not rows:
            return 0
        cols = list(rows[0].keys())
        conn = self.connection_factory()
        try:
            ph = ", ".join(["?"] * len(cols))
            sql = (f"INSERT INTO {self.table} "
                   f"({', '.join(cols)}) VALUES ({ph})")
            conn.executemany(
                sql, [tuple(r.get(c) for c in cols) for r in rows])
            conn.commit()
        finally:
            conn.close()
        return len(rows)


def write_sql(ds: Dataset, table: str,
              connection_factory: Callable[[], Any]) -> List[Any]:
    from .datasource import write_datasink

    return write_datasink(ds, SQLDatasink(table, connection_factory))


# ---------------------------------------------------------------------------
# Iceberg-class table formats (reference: read_iceberg / read_delta /
# read_lance). File-level partitioning: one ReadTask per data file the
# table's current snapshot references.
# ---------------------------------------------------------------------------

class IcebergDatasource(Datasource):
    def __init__(self, table_identifier: str, *,
                 row_filter: Any = None,
                 catalog_factory: Optional[Callable[[], Any]] = None):
        self.table_identifier = table_identifier
        self.row_filter = row_filter
        self.catalog_factory = catalog_factory or (
            lambda: _require("pyiceberg.catalog", "read_iceberg")
            .load_catalog("default"))

    def get_name(self) -> str:
        return f"iceberg({self.table_identifier})"

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        catalog = self.catalog_factory()
        table = catalog.load_table(self.table_identifier)
        scan = (table.scan(row_filter=self.row_filter)
                if self.row_filter is not None else table.scan())
        files = list(scan.plan_files())
        if not files:
            return [ReadTask(lambda: [])]

        def make(task_file):
            def read():
                # Each planned file scans independently (pyiceberg
                # returns arrow through to_arrow on a per-file scan).
                return task_file.to_arrow().to_pylist() \
                    if hasattr(task_file, "to_arrow") else \
                    _read_parquet_rows(task_file.file.file_path)
            return read

        return [ReadTask(make(f)) for f in files]


def _read_parquet_rows(path: str) -> List[Dict]:
    import pandas as pd

    return pd.read_parquet(path).to_dict("records")


def read_iceberg(table_identifier: str, *, row_filter=None,
                 parallelism: int = 8, catalog_factory=None) -> Dataset:
    return read_datasource(
        IcebergDatasource(table_identifier, row_filter=row_filter,
                          catalog_factory=catalog_factory),
        parallelism=parallelism)


class DeltaDatasource(Datasource):
    """Delta-style table: reads the current-version parquet file set
    (via deltalake when installed, or an injected table_factory)."""

    def __init__(self, table_uri: str, *,
                 table_factory: Optional[Callable[[], Any]] = None):
        self.table_uri = table_uri
        self.table_factory = table_factory or (
            lambda: _require("deltalake", "read_delta")
            .DeltaTable(table_uri))

    def get_name(self) -> str:
        return f"delta({self.table_uri})"

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        table = self.table_factory()
        files = list(table.file_uris())
        if not files:
            return [ReadTask(lambda: [])]

        def make(path):
            return ReadTask(lambda: _read_parquet_rows(path))

        return [make(f) for f in files]


def read_delta(table_uri: str, *, parallelism: int = 8,
               table_factory=None) -> Dataset:
    return read_datasource(
        DeltaDatasource(table_uri, table_factory=table_factory),
        parallelism=parallelism)


# ---------------------------------------------------------------------------
# ClickHouse / Snowflake (reference: clickhouse_datasource.py,
# snowflake_datasource.py) — query-partitioned like BigQuery.
# ---------------------------------------------------------------------------

def read_clickhouse(table: str, dsn: str, *, columns=None,
                    order_by: Optional[str] = None,
                    parallelism: int = 8, client_factory=None
                    ) -> Dataset:
    """LIMIT/OFFSET partitioning needs a total order (ClickHouse result
    order is undefined without ORDER BY): pass order_by to parallelize;
    without it the read is one correct unpartitioned task."""
    cols = ", ".join(columns) if columns else "*"
    factory = client_factory or (
        lambda: _require("clickhouse_connect", "read_clickhouse")
        .get_client(dsn=dsn))

    class _CH(Datasource):
        def get_name(self):
            return f"clickhouse({table})"

        def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
            if order_by is None:
                def read_all():
                    c = factory()
                    res = c.query(f"SELECT {cols} FROM {table}")
                    names = res.column_names
                    return [dict(zip(names, row))
                            for row in res.result_rows]

                return [ReadTask(read_all)]
            client = factory()
            total = int(client.command(
                f"SELECT count() FROM {table}"))
            n = max(1, min(parallelism, total) if total else 1)
            per = (total + n - 1) // n if total else 0

            def make(off, lim):
                def read():
                    c = factory()
                    res = c.query(f"SELECT {cols} FROM {table} "
                                  f"ORDER BY {order_by} "
                                  f"LIMIT {lim} OFFSET {off}")
                    names = res.column_names
                    return [dict(zip(names, row))
                            for row in res.result_rows]
                return read

            return [ReadTask(make(i * per, per)) for i in range(n)
                    if total] or [ReadTask(lambda: [])]

    return read_datasource(_CH(), parallelism=parallelism)


def read_snowflake(sql: str, connection_parameters: Dict[str, Any], *,
                   parallelism: int = 8, connection_factory=None
                   ) -> Dataset:
    factory = connection_factory or (
        lambda: _require("snowflake.connector", "read_snowflake")
        .connect(**connection_parameters))

    class _SF(Datasource):
        def get_name(self):
            return "snowflake"

        def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
            # ONE execution, one transfer: stride-slicing across n
            # separate executions would depend on a row order the
            # engine does not guarantee (and pay n full transfers).
            # Result-batch partitioning (cursor.get_result_batches) is
            # the parallel upgrade path when the vendor package is
            # present.
            def read():
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(sql)
                    cols = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                finally:
                    conn.close()
                return [dict(zip(cols, r)) for r in rows]

            return [ReadTask(read)]

    return read_datasource(_SF(), parallelism=parallelism)


# ---------------------------------------------------------------------------
# Avro (reference: avro_datasource.py) — file-partitioned.
# ---------------------------------------------------------------------------

def read_avro(paths, *, parallelism: int = 8) -> Dataset:
    from .read_api import _expand_paths, _reader_dataset

    def read_one(path: str):
        fastavro = _require("fastavro", "read_avro")
        with open(path, "rb") as f:
            return list(fastavro.reader(f))

    return _reader_dataset(_expand_paths(paths), read_one, "read_avro")
