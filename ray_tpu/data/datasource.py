"""Datasource/Datasink — the data connector extension point.

Capability-equivalent of the reference's custom-connector ABCs
(reference: python/ray/data/datasource/datasource.py Datasource +
get_read_tasks/estimate_inmemory_data_size;
python/ray/data/datasource/datasink.py Datasink with the
on_write_start/write/on_write_complete/on_write_failed lifecycle):
third-party IO (mongo/bigquery-class connectors) plugs in WITHOUT
touching the built-in read_*/write_* functions.

- A Datasource turns itself into independent ReadTasks; each runs as
  one distributed task producing ONE block (a datasource wanting more
  output blocks returns more ReadTasks — block structure stays
  explicit instead of hiding a second fan-out inside a task).
- A Datasink is cloudpickled into per-block distributed write tasks;
  the driver runs the lifecycle hooks around them.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional

from .plan import Read


class ReadTask:
    """One unit of distributed read work: a zero-arg callable returning
    one block. num_rows/size_bytes are informational metadata carried
    for connector introspection — the planner does not consume them
    yet (declaring them load-bearing here would be a silent no-op)."""

    def __init__(self, fn: Callable[[], Any], *,
                 num_rows: Optional[int] = None,
                 size_bytes: Optional[int] = None):
        self._fn = fn
        self.num_rows = num_rows
        self.size_bytes = size_bytes

    def __call__(self) -> Any:
        return self._fn()


class Datasource(ABC):
    """Custom read connector (reference: datasource.py:18).

    Subclass and implement get_read_tasks (and, when cheap,
    estimate_inmemory_data_size); pass an instance to
    read_datasource()."""

    def get_name(self) -> str:
        name = type(self).__name__
        if name.endswith("Datasource"):
            name = name[: -len("Datasource")]
        return name

    @abstractmethod
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        """Split this source into at most `parallelism` ReadTasks
        (fewer is fine — e.g. one per file/shard/partition)."""

    def estimate_inmemory_data_size(self) -> Optional[int]:
        """Estimated decompressed in-memory bytes, or None.
        Informational (callers/tests may read it); the planner does
        not consume it yet."""
        return None


class Datasink(ABC):
    """Custom write connector (reference: datasink.py:9).

    write() runs as a distributed task per block — the instance is
    serialized into each task, so keep per-run mutable state out of it
    and return per-block results instead; the driver-side lifecycle
    hooks see all of them."""

    def on_write_start(self) -> None:
        """Driver-side, before any write task is submitted."""

    @abstractmethod
    def write(self, block: Any) -> Any:
        """Write one block (runs remotely); the return value is
        collected into on_write_complete's list."""

    def on_write_complete(self, write_results: List[Any]) -> None:
        """Driver-side, after every write task succeeded."""

    def on_write_failed(self, error: Exception) -> None:
        """Driver-side, when any write task failed (before the error
        re-raises)."""

    def get_name(self) -> str:
        name = type(self).__name__
        if name.endswith("Datasink"):
            name = name[: -len("Datasink")]
        return name


def read_datasource(datasource: Datasource, *,
                    parallelism: int = -1):
    """Dataset from a custom Datasource (reference:
    read_api.read_datasource)."""
    from .dataset import Dataset

    if parallelism <= 0:
        parallelism = 200  # reference default ceiling for auto
    tasks = datasource.get_read_tasks(parallelism)
    if not tasks:
        raise ValueError(
            f"{datasource.get_name()}: get_read_tasks returned no work")
    return Dataset(Read(list(tasks), datasource.get_name()))


def write_datasink(ds, datasink: Datasink) -> List[Any]:
    """Write every block of `ds` through a custom Datasink; returns the
    per-block write results (reference: Dataset.write_datasink)."""
    from .. import get as ray_get, remote

    @remote
    def _write(block, sink):
        return sink.write(block)

    datasink.on_write_start()
    try:
        refs = [_write.remote(ref, datasink) for ref in ds._refs()]
        results = list(ray_get(refs))
    except Exception as e:  # noqa: BLE001 — surface via the hook, then raise
        datasink.on_write_failed(e)
        raise
    datasink.on_write_complete(results)
    return results


__all__ = ["Datasource", "Datasink", "ReadTask", "read_datasource",
           "write_datasink"]
