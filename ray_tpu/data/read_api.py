"""Dataset creation: in-memory builders + file readers.

Capability-equivalent to the reference's read API
(reference: python/ray/data/read_api.py read_* builders and
data/datasource/ — parquet/csv/json/text/binary/images readers; the
Datasource/Datasink ABCs live in datasource.py here)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .block import BlockAccessor
from .dataset import Dataset
from .plan import FromBlocks, Read


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, f"**/*{suffix or ''}")
            out.extend(sorted(_glob.glob(pat, recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return [p for p in out if os.path.isfile(p)]


def _reader_dataset(paths: List[str], read_one: Callable[[str], Any],
                    name: str, parallelism: int = -1) -> Dataset:
    if not paths:
        raise ValueError(f"{name}: no input files found")
    tasks = [(lambda p=p: read_one(p)) for p in paths]
    return Dataset(Read(tasks, name))


# ---------------------------------------------------------------------------
# In-memory
# ---------------------------------------------------------------------------

def from_items(items: List[Any]) -> Dataset:
    rows = [i if isinstance(i, dict) else {"item": i} for i in items]
    block = BlockAccessor.for_block(rows).block
    return Dataset(FromBlocks([block], "from_items"))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    per = max(1, n // max(1, parallelism))
    tasks = []
    start = 0
    while start < n:
        end = min(start + per, n)
        tasks.append(lambda s=start, e=end: {"id": np.arange(s, e)})
        start = end
    return Dataset(Read(tasks, f"range({n})"))


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    per = max(1, n // max(1, parallelism))
    tasks = []
    start = 0
    while start < n:
        end = min(start + per, n)

        def mk(s=start, e=end):
            count = e - s
            data = np.broadcast_to(
                np.arange(s, e).reshape((count,) + (1,) * len(shape)),
                (count,) + tuple(shape)).copy()
            return {"data": data}

        tasks.append(mk)
        start = end
    return Dataset(Read(tasks, f"range_tensor({n})"))


def from_pandas(df) -> Dataset:
    return Dataset(FromBlocks(
        [BlockAccessor.for_block(df).block], "from_pandas"))


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    return Dataset(FromBlocks(
        [BlockAccessor.for_block({column: arr}).block], "from_numpy"))


def from_arrow(table) -> Dataset:
    return Dataset(FromBlocks([table], "from_arrow"))


def from_torch(torch_dataset) -> Dataset:
    """Map-style torch Dataset → Dataset (reference:
    read_api.from_torch). Rows become {"item": value} unless the
    dataset yields dicts."""
    import builtins

    def to_np(x):
        if hasattr(x, "detach"):  # torch tensor: device/grad-safe path
            x = x.detach().cpu().numpy()
        elif hasattr(x, "numpy"):
            x = x.numpy()
        if isinstance(x, np.ndarray) and x.ndim == 0:
            x = x.item()
        return x

    rows = []
    # NB: this module's `range` is the dataset builder.
    for i in builtins.range(len(torch_dataset)):
        item = torch_dataset[i]
        if isinstance(item, dict):
            rows.append({k: to_np(v) for k, v in item.items()})
            continue
        if isinstance(item, tuple):
            # Tuples are the multi-output convention (TensorDataset);
            # plain lists stay one value (e.g. variable-length tokens).
            vals = [to_np(x) for x in item]
            rows.append({"item": vals[0]} if len(vals) == 1 else
                        {f"item_{j}": v for j, v in enumerate(vals)})
            continue
        rows.append({"item": to_np(item)})
    return Dataset(FromBlocks(
        [BlockAccessor.for_block(rows).block], "from_torch"))


def from_huggingface(hf_dataset) -> Dataset:
    """HuggingFace datasets.Dataset → Dataset via its arrow table
    (reference: read_api.from_huggingface; zero-copy when possible)."""
    # A shuffled/selected/filtered HF dataset keeps the ORIGINAL rows
    # in .data and records the view in _indices — materialize the view
    # first or we'd silently return wrong rows.
    orig = hf_dataset
    table = None
    if getattr(hf_dataset, "_indices", None) is not None:
        try:
            hf_dataset = hf_dataset.flatten_indices()
        except Exception:  # noqa: BLE001 - fall back to row iteration
            hf_dataset = None
    if hf_dataset is not None:
        table = getattr(getattr(hf_dataset, "data", None), "table", None)
    if table is not None:
        return Dataset(FromBlocks([table], "from_huggingface"))
    hf_dataset = orig  # row-iteration fallback sees the user's VIEW
    # Fallback: row iteration (datasets lib variants without .data).
    rows = [dict(r) for r in hf_dataset]
    return Dataset(FromBlocks(
        [BlockAccessor.for_block(rows).block], "from_huggingface"))


# ---------------------------------------------------------------------------
# Files
# ---------------------------------------------------------------------------

def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 parallelism: int = -1) -> Dataset:
    import pyarrow.parquet as pq

    files = _expand_paths(paths, ".parquet")

    def read_one(path):
        return pq.read_table(path, columns=columns)

    return _reader_dataset(files, read_one, "read_parquet")


def read_csv(paths, *, parallelism: int = -1, **csv_kwargs) -> Dataset:
    import pyarrow.csv as pacsv

    files = _expand_paths(paths, ".csv")

    def read_one(path):
        return pacsv.read_csv(path)

    return _reader_dataset(files, read_one, "read_csv")


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    import pyarrow.json as pajson

    files = _expand_paths(paths)

    def read_one(path):
        return pajson.read_json(path)

    return _reader_dataset(files, read_one, "read_json")


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with open(path, "r") as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": np.array(lines, dtype=object)}

    return _reader_dataset(files, read_one, "read_text")


def read_binary_files(paths, *, include_paths: bool = False,
                      parallelism: int = -1) -> Dataset:
    files = _expand_paths(paths)

    def read_one(path):
        with open(path, "rb") as f:
            data = f.read()
        row: Dict[str, Any] = {"bytes": [data]}
        if include_paths:
            row["path"] = [path]
        return row

    return _reader_dataset(files, read_one, "read_binary_files")


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def read_one(path):
        return {"data": np.load(path)}

    return _reader_dataset(files, read_one, "read_numpy")


def read_images(paths, *, size: Optional[tuple] = None,
                parallelism: int = -1) -> Dataset:
    """Image reader (ViT/CLIP ingest path, BASELINE config 4). Decodes
    via PIL when available; raw-npy fallback."""
    files = _expand_paths(paths)

    def read_one(path):
        try:
            from PIL import Image

            img = Image.open(path).convert("RGB")
            if size is not None:
                img = img.resize(size)
            return {"image": np.asarray(img)[None]}
        except ImportError:
            return {"image": np.load(path)[None]}

    return _reader_dataset(files, read_one, "read_images")


def read_tfrecords(paths, *, parallelism: int = -1,
                   verify_crc: Optional[bool] = None) -> Dataset:
    """TFRecord reader — pure-python wire format + tf.train.Example codec
    (reference: data/datasource/tfrecords_datasource.py, sans tensorflow).
    Set verify_crc=False to skip checksums on trusted large shards."""
    from . import tfrecord
    from .context import DataContext

    if verify_crc is None:
        verify_crc = DataContext.get_current().tfrecord_verify_crc
    files = _expand_paths(paths)

    def read_one(path):
        examples = [tfrecord.decode_example(p)
                    for p in tfrecord.read_records(path, verify=verify_crc)]
        return tfrecord.examples_to_batch(examples)

    return _reader_dataset(files, read_one, "read_tfrecords")


def read_webdataset(paths, *, parallelism: int = -1,
                    decode: bool = True) -> Dataset:
    """WebDataset tar reader: files sharing a basename form one sample,
    keyed by extension (reference: data/datasource/webdataset_datasource.py).
    """
    files = _expand_paths(paths, ".tar")

    def read_one(path):
        import tarfile

        rows: List[Dict[str, Any]] = []
        cur: Dict[str, Any] = {}
        cur_key = None
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                base, _, ext = os.path.basename(member.name).partition(".")
                if base != cur_key:
                    if cur:
                        rows.append(cur)
                    cur, cur_key = {"__key__": base}, base
                data = tf.extractfile(member).read()
                cur[ext] = _wds_decode(ext, data) if decode else data
        if cur:
            rows.append(cur)
        return rows

    return _reader_dataset(files, read_one, "read_webdataset")


def _wds_decode(ext: str, data: bytes):
    import json as _json

    if ext in ("json",):
        return _json.loads(data)
    if ext in ("txt", "text", "cls2", "info"):
        return data.decode()
    if ext in ("cls", "index", "id"):
        try:
            return int(data.decode().strip())
        except ValueError:
            return data.decode()
    if ext in ("npy",):
        import io

        return np.load(io.BytesIO(data))
    if ext in ("jpg", "jpeg", "png", "ppm", "webp"):
        try:
            import io

            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        except ImportError:
            return data
    return data


def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             parallelism: int = -1) -> Dataset:
    """Read from any DBAPI2 source (reference:
    data/datasource/sql_datasource.py). `connection_factory` returns a new
    DBAPI connection (e.g. `lambda: sqlite3.connect(path)`)."""

    def read_one(_):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return [dict(zip(cols, r)) for r in rows] or {
            c: np.array([]) for c in cols}

    return Dataset(Read([lambda: read_one(None)], "read_sql"))


# ---------------------------------------------------------------------------
# Datasinks (parallel writes — one remote task per block; reference:
# python/ray/data/datasource/datasink.py + Dataset.write_*)
# ---------------------------------------------------------------------------

def _write_blocks(ds: Dataset, path: str, write_one: Callable[[Any, str], None],
                  ext: str) -> List[str]:
    from .. import get as ray_get, remote

    os.makedirs(path, exist_ok=True)

    @remote
    def _task(block, out):
        write_one(block, out)
        return out

    refs = []
    for i, ref in enumerate(ds._refs()):
        out = os.path.join(path, f"part-{i:05d}{ext}")
        refs.append(_task.remote(ref, out))
    return list(ray_get(refs))


def write_parquet(ds: Dataset, path: str) -> List[str]:
    import pyarrow.parquet as pq

    return _write_blocks(
        ds, path, lambda b, out: pq.write_table(b, out), ".parquet")


def write_csv(ds: Dataset, path: str) -> List[str]:
    import pyarrow.csv as pacsv

    return _write_blocks(
        ds, path, lambda b, out: pacsv.write_csv(b, out), ".csv")


def write_json(ds: Dataset, path: str) -> List[str]:
    import json as _json

    def _w(block, out):
        rows = BlockAccessor.for_block(block).iter_rows()
        with open(out, "w") as f:
            for r in rows:
                f.write(_json.dumps(
                    {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                     for k, v in r.items()}) + "\n")

    return _write_blocks(ds, path, _w, ".jsonl")


def write_numpy(ds: Dataset, path: str, column: str) -> List[str]:
    def _w(block, out):
        batch = BlockAccessor.for_block(block).to_batch("numpy")
        np.save(out, batch[column])

    return _write_blocks(ds, path, _w, ".npy")


def write_tfrecords(ds: Dataset, path: str) -> List[str]:
    from . import tfrecord

    def _w(block, out):
        batch = BlockAccessor.for_block(block).to_batch("numpy")
        tfrecord.write_records(out, tfrecord.batch_to_examples(batch))

    return _write_blocks(ds, path, _w, ".tfrecords")
