"""Aggregation functions for Dataset / GroupedData.

Capability-equivalent to the reference's aggregate vocabulary
(reference: python/ray/data/aggregate.py — AggregateFn, Count, Sum, Min,
Max, Mean, Std, AbsMax, Quantile, Unique): each aggregate is defined by
(init, per-block accumulate, cross-block merge, finalize) so blocks can
be reduced in parallel remote tasks and combined on the driver.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import numpy as np

from .block import BlockAccessor


class AggregateFn:
    """(init, accumulate_block, merge, finalize) over a column."""

    def __init__(self, *, init: Callable[[], Any],
                 accumulate_block: Callable[[Any, Any], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Callable[[Any], Any] = lambda a: a,
                 name: str = "agg"):
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize
        self.name = name

    # pyarrow group_by kernel this aggregate maps to, if any (used by the
    # grouped fast path); None → generic accumulate path.
    arrow_kernel: Optional[str] = None
    arrow_options: Optional[Any] = None
    on: Optional[str] = None


def _col(block, on: str) -> np.ndarray:
    acc = BlockAccessor.for_block(block)
    batch = acc.to_batch("numpy")
    if on not in batch:
        raise KeyError(f"no column {on!r}; have {list(batch)}")
    return np.asarray(batch[on])


class Count(AggregateFn):
    arrow_kernel = "count"

    def __init__(self):
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda a, b: a + BlockAccessor.for_block(
                b).num_rows(),
            merge=lambda a, b: a + b,
            name="count()")


class Sum(AggregateFn):
    arrow_kernel = "sum"

    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda a, b: a + _col(b, on).sum(),
            merge=lambda a, b: a + b,
            name=f"sum({on})")


class Min(AggregateFn):
    arrow_kernel = "min"

    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: None,
            accumulate_block=lambda a, b: _opt_reduce(min, a, _col(b, on)),
            merge=lambda a, b: _opt(min, a, b),
            name=f"min({on})")


class Max(AggregateFn):
    arrow_kernel = "max"

    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: None,
            accumulate_block=lambda a, b: _opt_reduce(max, a, _col(b, on)),
            merge=lambda a, b: _opt(max, a, b),
            name=f"max({on})")


class AbsMax(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: None,
            accumulate_block=lambda a, b: _opt_reduce(
                max, a, np.abs(_col(b, on))),
            merge=lambda a, b: _opt(max, a, b),
            name=f"abs_max({on})")


class Mean(AggregateFn):
    arrow_kernel = "mean"

    def __init__(self, on: str):
        self.on = on

        def acc(a, b):
            c = _col(b, on)
            return (a[0] + float(c.sum()), a[1] + len(c))

        super().__init__(
            init=lambda: (0.0, 0),
            accumulate_block=acc,
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda a: a[0] / a[1] if a[1] else float("nan"),
            name=f"mean({on})")


class Std(AggregateFn):
    """Sample std via parallel Welford / Chan merge."""

    arrow_kernel = "stddev"

    def __init__(self, on: str, ddof: int = 1):
        import pyarrow.compute as pc

        self.on = on
        self.ddof = ddof
        self.arrow_options = pc.VarianceOptions(ddof=ddof)

        def acc(a, block):
            x = _col(block, on).astype(np.float64)
            n, mean, m2 = len(x), float(x.mean()) if len(x) else 0.0, \
                float(((x - x.mean()) ** 2).sum()) if len(x) else 0.0
            return _chan(a, (n, mean, m2))

        super().__init__(
            init=lambda: (0, 0.0, 0.0),
            accumulate_block=acc,
            merge=_chan,
            finalize=lambda a: (
                math.sqrt(a[2] / (a[0] - ddof)) if a[0] > ddof
                else float("nan")),
            name=f"std({on})")


class Unique(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: set(),
            accumulate_block=lambda a, b: a | set(
                np.asarray(_col(b, on)).tolist()),
            merge=lambda a, b: a | b,
            finalize=lambda a: sorted(a),
            name=f"unique({on})")


class Quantile(AggregateFn):
    """Exact quantile (collects the column; fine for block-scale data)."""

    def __init__(self, on: str, q: float = 0.5):
        self.on = on
        self.q = q
        super().__init__(
            init=lambda: [],
            accumulate_block=lambda a, b: a + _col(b, on).tolist(),
            merge=lambda a, b: a + b,
            finalize=lambda a: (
                float(np.quantile(np.asarray(a), q)) if a else float("nan")),
            name=f"quantile({on},q={q})")


def _opt(op, a, b):
    if a is None:
        return b
    if b is None:
        return a
    return op(a, b)


def _opt_reduce(op, acc, col: np.ndarray):
    """Fold a column into acc, skipping empty blocks (min/max of a
    zero-size array has no identity)."""
    if len(col) == 0:
        return acc
    return _opt(op, acc, col.min() if op is min else col.max())


def _chan(a, b):
    """Chan et al. parallel variance merge of (n, mean, M2) triples."""
    na, ma, m2a = a
    nb, mb, m2b = b
    n = na + nb
    if n == 0:
        return (0, 0.0, 0.0)
    delta = mb - ma
    mean = ma + delta * nb / n
    m2 = m2a + m2b + delta * delta * na * nb / n
    return (n, mean, m2)


def reduce_blocks(agg: AggregateFn, blocks) -> Any:
    acc = agg.init()
    for b in blocks:
        acc = agg.accumulate_block(acc, b)
    return acc
