"""Dataset — the public lazy distributed data API.

Capability-equivalent to the reference's Dataset
(reference: python/ray/data/dataset.py:158 — map_batches :412,
streaming_split :1272, iter_batches :3720, materialize :4694, plus
map/filter/flat_map/limit/take/count/schema/repartition/random_shuffle/
sort/union/split/zip surface): a chain of logical ops executed by the
streaming executor on the task/actor runtime.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .block import BlockAccessor, concat_blocks
from .executor import execute
from .iterator import DataIterator, SplitIterator, _SplitState
from .plan import (
    FromBlocks,
    Limit,
    LogicalOp,
    MapLike,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    Union,
    _MapSpec,
)


class Dataset:
    def __init__(self, op: LogicalOp):
        self._op = op
        self._materialized: Optional[List] = None

    # ------------------------------------------------------------------
    # Transforms (lazy)
    # ------------------------------------------------------------------
    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    compute: Optional[str] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    num_cpus: float = 1, num_tpus: float = 0,
                    concurrency=None) -> "Dataset":
        """concurrency: int = fixed class-UDF actor pool; (min, max)
        tuple = autoscaling pool that grows when inputs queue
        (reference: ActorPoolStrategy(min_size, max_size))."""
        spec = _MapSpec("batches", fn, batch_size, batch_format,
                        fn_constructor_args, fn_constructor_kwargs or {})
        return Dataset(MapLike(self._op, spec, compute=compute,
                               num_cpus=num_cpus, num_tpus=num_tpus,
                               concurrency=concurrency))

    def map(self, fn: Callable, **kwargs) -> "Dataset":
        return Dataset(MapLike(self._op, _MapSpec("rows", fn), **_mk(kwargs)))

    def filter(self, fn: Callable, **kwargs) -> "Dataset":
        return Dataset(
            MapLike(self._op, _MapSpec("filter", fn), **_mk(kwargs)))

    def flat_map(self, fn: Callable, **kwargs) -> "Dataset":
        return Dataset(MapLike(self._op, _MapSpec("flat", fn), **_mk(kwargs)))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def _add(batch):
            batch[name] = fn(batch)
            return batch
        return self.map_batches(_add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def _drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}
        return self.map_batches(_drop)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def _sel(batch):
            return {k: batch[k] for k in cols}
        return self.map_batches(_sel)

    def limit(self, n: int) -> "Dataset":
        return Dataset(Limit(self._op, n))

    def repartition(self, n: int) -> "Dataset":
        return Dataset(Repartition(self._op, n))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(RandomShuffle(self._op, seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(Sort(self._op, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(Union(self._op, [o._op for o in others]))

    # ------------------------------------------------------------------
    # GroupBy / aggregation (reference: dataset.py groupby :2213,
    # aggregate/sum/min/max/mean/std :2281-2554, unique)
    # ------------------------------------------------------------------
    def groupby(self, key: str, *, num_partitions: Optional[int] = None):
        from .grouped_data import GroupedData

        return GroupedData(self, key, num_partitions)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Whole-dataset aggregation: per-block parallel accumulate +
        driver-side merge (reference: Dataset.aggregate)."""
        from .. import get as ray_get, remote

        @remote
        def _acc_block(block, aggs_):
            return [a.accumulate_block(a.init(), block) for a in aggs_]

        aggs_l = list(aggs)
        refs = [_acc_block.remote(r, aggs_l) for r in self._refs()]
        states = [a.init() for a in aggs_l]
        for partials in ray_get(refs):
            states = [a.merge(s, p)
                      for a, s, p in zip(aggs_l, states, partials)]
        return {a.name: a.finalize(s) for a, s in zip(aggs_l, states)}

    def sum(self, on: str):
        from .aggregate import Sum

        return self.aggregate(Sum(on))[f"sum({on})"]

    def min(self, on: str):
        from .aggregate import Min

        return self.aggregate(Min(on))[f"min({on})"]

    def max(self, on: str):
        from .aggregate import Max

        return self.aggregate(Max(on))[f"max({on})"]

    def mean(self, on: str):
        from .aggregate import Mean

        return self.aggregate(Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        from .aggregate import Std

        return self.aggregate(Std(on, ddof))[f"std({on})"]

    def unique(self, on: str) -> List[Any]:
        from .aggregate import Unique

        return self.aggregate(Unique(on))[f"unique({on})"]

    # ------------------------------------------------------------------
    # Writes (reference: Dataset.write_parquet :2774 etc.)
    # ------------------------------------------------------------------
    def write_parquet(self, path: str) -> List[str]:
        from .read_api import write_parquet

        return write_parquet(self, path)

    def write_csv(self, path: str) -> List[str]:
        from .read_api import write_csv

        return write_csv(self, path)

    def write_json(self, path: str) -> List[str]:
        from .read_api import write_json

        return write_json(self, path)

    def write_numpy(self, path: str, *, column: str) -> List[str]:
        from .read_api import write_numpy

        return write_numpy(self, path, column)

    def write_tfrecords(self, path: str) -> List[str]:
        from .read_api import write_tfrecords

        return write_tfrecords(self, path)

    def write_datasink(self, datasink) -> List[Any]:
        """Write through a custom Datasink connector (reference:
        Dataset.write_datasink)."""
        from .datasource import write_datasink

        return write_datasink(self, datasink)

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------
    def _refs(self) -> Iterator:
        if self._materialized is not None:
            return iter(self._materialized)
        from .executor import ExecStats

        self._last_stats = ExecStats()
        return execute(self._op, stats=self._last_stats)

    def stats(self) -> str:
        """Execution statistics of the most recent run of this dataset
        (reference: dataset.py Dataset.stats()). Stages report blocks
        produced and pipelined wall time."""
        st = getattr(self, "_last_stats", None)
        if st is None:
            return "No execution stats: dataset has not been executed."
        return st.summary()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return DataIterator(self._refs).iter_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        return DataIterator(self._refs).iter_torch_batches(**kwargs)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return DataIterator(self._refs).iter_rows()

    def iterator(self) -> DataIterator:
        return DataIterator(self._refs)

    def streaming_split(self, n: int, *, equal: bool = True
                        ) -> List[SplitIterator]:
        """One shared streaming execution feeding n consumers
        (reference: dataset.py:1272)."""
        state = _SplitState(self._refs(), n, equal)
        return [SplitIterator(state, i) for i in range(n)]

    def materialize(self) -> "Dataset":
        from .. import get as ray_get, put as ray_put

        # one batched get: a per-ref get would block on each block in
        # submission order while later ones sit ready
        blocks = [ray_put(b) for b in ray_get(list(self._refs()))]
        out = Dataset(FromBlocks(blocks, "materialized"))
        out._materialized = blocks
        return out

    # -- consumption ----------------------------------------------------
    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        from .. import get as ray_get

        return sum(BlockAccessor.for_block(b).num_rows()
                   for b in ray_get(list(self._refs())))

    def schema(self):
        from .. import get as ray_get

        for ref in self._refs():
            block = ray_get(ref)
            if block.num_rows or block.schema.names:
                return block.schema
        return None

    def to_pandas(self):
        from .. import get as ray_get

        blocks = ray_get(list(self._refs()))
        return concat_blocks(blocks).to_pandas()

    def split(self, n: int) -> List["Dataset"]:
        from .. import get as ray_get, put as ray_put

        blocks = [ray_get(r) for r in self._refs()]
        merged = concat_blocks(blocks)
        rows = merged.num_rows
        per = rows // n
        out = []
        start = 0
        for i in range(n):
            end = rows if i == n - 1 else start + per
            ref = ray_put(merged.slice(start, end - start))
            d = Dataset(FromBlocks([ref], f"split_{i}"))
            d._materialized = [ref]
            out.append(d)
            start = end
        return out

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Split at global row indices (reference: dataset.py
        split_at_indices). len(indices)+1 datasets."""
        from .. import get as ray_get, put as ray_put

        if any(i < 0 for i in indices) or list(indices) != sorted(indices):
            raise ValueError("indices must be non-negative and sorted")
        merged = concat_blocks([ray_get(r) for r in self._refs()])
        rows = merged.num_rows
        bounds = [0] + [min(i, rows) for i in indices] + [rows]
        out = []
        for i in range(len(bounds) - 1):
            start, end = bounds[i], max(bounds[i], bounds[i + 1])
            ref = ray_put(merged.slice(start, end - start))
            d = Dataset(FromBlocks([ref], f"split_at_{i}"))
            d._materialized = [ref]
            out.append(d)
        return out

    def train_test_split(self, test_size, *,
                         shuffle: bool = False, seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        """(train, test) split by fraction or absolute test-row count
        (reference: dataset.py train_test_split — float in (0,1) or
        int number of test rows)."""
        is_int = isinstance(test_size, (int, np.integer)) \
            and not isinstance(test_size, bool)
        if is_int:
            if test_size <= 0:
                raise ValueError("int test_size must be positive")
        elif not 0 < test_size < 1:
            raise ValueError("float test_size must be in (0, 1)")
        # Materialize ONCE before counting: count() + split_at_indices()
        # on a lazy pipeline would execute it twice — wrong row counts
        # if any stage is nondeterministic, double work otherwise.
        ds = (self.random_shuffle(seed=seed) if shuffle
              else self).materialize()
        n = ds.count()
        if is_int:
            if test_size >= n:
                raise ValueError(
                    f"test_size {test_size} must be < dataset size {n}")
            cut = n - test_size
        else:
            cut = n - int(n * test_size)
        train, test = ds.split_at_indices([cut])
        return train, test

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: dataset.py random_sample)."""
        if not 0 <= fraction <= 1:
            raise ValueError("fraction must be in [0, 1]")
        # Per-block sampling (reference random_sample does the same).
        # Each block folds a digest of its own bytes into the seed, so
        # equal-sized blocks draw INDEPENDENT masks (a bare shared seed
        # would select identical row positions in every block) while the
        # overall sample stays deterministic for a given dataset+seed.
        def _sample(batch):
            import zlib

            cols = dict(batch)
            n = len(next(iter(cols.values()))) if cols else 0
            if seed is None:
                rng = np.random.default_rng()
            else:
                salt = 0
                for k in sorted(cols):
                    a = np.asarray(cols[k])
                    if a.dtype == object:
                        a = np.asarray([str(x) for x in a.ravel()[:256]],
                                       dtype="U")
                    # Slice BEFORE tobytes: a full-column copy per block
                    # just to CRC 64 KB would dominate the sample cost.
                    flat = a.ravel()[:65536 // max(1, a.itemsize)]
                    salt = zlib.crc32(
                        np.ascontiguousarray(flat).tobytes(), salt)
                rng = np.random.default_rng([seed, n, salt])
            keep = rng.random(n) < fraction
            return {k: np.asarray(v)[keep] for k, v in cols.items()}

        return self.map_batches(_sample)

    def zip(self, other: "Dataset") -> "Dataset":
        from .. import get as ray_get, put as ray_put
        import pyarrow as pa

        a = concat_blocks([ray_get(r) for r in self._refs()])
        b = concat_blocks([ray_get(r) for r in other._refs()])
        n = min(a.num_rows, b.num_rows)
        a, b = a.slice(0, n), b.slice(0, n)
        cols = {}
        for name in a.column_names:
            cols[name] = a.column(name)
        for name in b.column_names:
            key = name if name not in cols else f"{name}_1"
            cols[key] = b.column(name)
        ref = ray_put(pa.table(cols))
        d = Dataset(FromBlocks([ref], "zip"))
        d._materialized = [ref]
        return d

    def __repr__(self):
        return f"Dataset({' -> '.join(op.name for op in self._op.chain())})"


def _mk(kwargs):
    return {k: v for k, v in kwargs.items()
            if k in ("compute", "num_cpus", "num_tpus", "concurrency")}
