"""TFRecord file format — pure-python reader/writer, no TensorFlow.

Capability-equivalent of the reference's TFRecords datasource
(reference: python/ray/data/datasource/tfrecords_datasource.py, which
delegates to tensorflow). Implemented from the public wire formats:

- TFRecord framing: [uint64 length][uint32 masked-crc32c(length)]
  [data][uint32 masked-crc32c(data)], little-endian; mask(c) =
  ((c >> 15) | (c << 17)) + 0xa282ead8.
- Payloads are `tf.train.Example` protobufs: Example{ Features{
  map<string, Feature> feature }} with Feature one of BytesList(field 1)
  / FloatList(2) / Int64List(3) — encoded/decoded here with a minimal
  protobuf wire codec (varint + length-delimited only).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# ---------------------------------------------------------------------------
# CRC32-C (Castagnoli), table-driven
# ---------------------------------------------------------------------------

_POLY = 0x82F63B78
_TABLES = None


def _tables():
    """Slice-by-8 lookup tables (plain lists — list indexing beats numpy
    scalar indexing in the per-word loop)."""
    global _TABLES
    if _TABLES is None:
        t0 = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (_POLY if c & 1 else 0)
            t0.append(c)
        tables = [t0]
        for k in range(1, 8):
            prev = tables[k - 1]
            tables.append([(prev[i] >> 8) ^ t0[prev[i] & 0xFF]
                           for i in range(256)])
        _TABLES = tables
    return _TABLES


def crc32c(data: bytes) -> int:
    """Slice-by-8 CRC32-C: one loop iteration per 8 input bytes."""
    t0, t1, t2, t3, t4, t5, t6, t7 = _tables()
    crc = 0xFFFFFFFF
    n = len(data)
    n8 = n - (n % 8)
    if n8:
        words = np.frombuffer(data[:n8], dtype="<u8").tolist()
        for w in words:
            x = (crc ^ (w & 0xFFFFFFFF)) & 0xFFFFFFFF
            hi = w >> 32
            crc = (t7[x & 0xFF] ^ t6[(x >> 8) & 0xFF]
                   ^ t5[(x >> 16) & 0xFF] ^ t4[x >> 24]
                   ^ t3[hi & 0xFF] ^ t2[(hi >> 8) & 0xFF]
                   ^ t1[(hi >> 16) & 0xFF] ^ t0[hi >> 24])
    for b in data[n8:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Record framing
# ---------------------------------------------------------------------------

def write_records(path: str, payloads: List[bytes]) -> None:
    with open(path, "wb") as f:
        for data in payloads:
            length = struct.pack("<Q", len(data))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc(length)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))


def read_records(path: str, *, verify: bool = True) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            (lcrc,) = struct.unpack("<I", header[8:])
            if verify and _masked_crc(header[:8]) != lcrc:
                raise ValueError(f"{path}: corrupt record length crc")
            data = f.read(length)
            tail = f.read(4)
            if len(data) < length or len(tail) < 4:
                raise ValueError(f"{path}: truncated record payload")
            (dcrc,) = struct.unpack("<I", tail)
            if verify and _masked_crc(data) != dcrc:
                raise ValueError(f"{path}: corrupt record data crc")
            yield data


# ---------------------------------------------------------------------------
# Minimal protobuf wire codec (varint + length-delimited)
# ---------------------------------------------------------------------------

def _put_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _get_varint(buf: bytes, i: int):
    shift = v = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _put_tag(out: bytearray, field: int, wire: int) -> None:
    _put_varint(out, (field << 3) | wire)


def _put_bytes_field(out: bytearray, field: int, data: bytes) -> None:
    _put_tag(out, field, 2)
    _put_varint(out, len(data))
    out.extend(data)


def _encode_feature(value) -> bytes:
    """Feature{ bytes_list=1 | float_list=2 | int64_list=3 }."""
    inner = bytearray()
    if isinstance(value, (bytes, bytearray, str)):
        value = [value]
    value = list(value)
    if value and isinstance(value[0], (bytes, bytearray, str)):
        # BytesList{ repeated bytes value = 1 }
        for v in value:
            if isinstance(v, str):
                v = v.encode()
            _put_bytes_field(inner, 1, bytes(v))
        field = 1
    elif value and isinstance(value[0], (float, np.floating)):
        # FloatList{ repeated float value = 1 [packed] }
        packed = struct.pack(f"<{len(value)}f", *[float(v) for v in value])
        _put_bytes_field(inner, 1, packed)
        field = 2
    else:
        # Int64List{ repeated int64 value = 1 [packed] }
        packed = bytearray()
        for v in value:
            _put_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        _put_bytes_field(inner, 1, bytes(packed))
        field = 3
    out = bytearray()
    _put_bytes_field(out, field, bytes(inner))
    return bytes(out)


def encode_example(features: Dict[str, Any]) -> bytes:
    """Example{ Features features = 1 }; Features{ map<string,Feature> }."""
    fmap = bytearray()
    for name, value in features.items():
        entry = bytearray()
        _put_bytes_field(entry, 1, name.encode())
        _put_bytes_field(entry, 2, _encode_feature(value))
        _put_bytes_field(fmap, 1, bytes(entry))
    out = bytearray()
    _put_bytes_field(out, 1, bytes(fmap))
    return bytes(out)


def _iter_fields(buf: bytes):
    i = 0
    while i < len(buf):
        tag, i = _get_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            ln, i = _get_varint(buf, i)
            yield field, buf[i:i + ln]
            i += ln
        elif wire == 0:
            v, i = _get_varint(buf, i)
            yield field, v
        elif wire == 5:
            yield field, buf[i:i + 4]
            i += 4
        elif wire == 1:
            yield field, buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _decode_feature(buf: bytes):
    for field, data in _iter_fields(buf):
        if field == 1:  # BytesList
            return [v for f, v in _iter_fields(data) if f == 1]
        if field == 2:  # FloatList (packed or unpacked)
            vals: List[float] = []
            for f, v in _iter_fields(data):
                if f == 1:
                    if isinstance(v, (bytes, bytearray)):
                        vals.extend(struct.unpack(f"<{len(v) // 4}f", v))
                    else:
                        vals.append(float(v))
            return np.asarray(vals, dtype=np.float32)
        if field == 3:  # Int64List (packed varints)
            vals = []
            for f, v in _iter_fields(data):
                if f == 1:
                    if isinstance(v, (bytes, bytearray)):
                        j = 0
                        while j < len(v):
                            x, j = _get_varint(v, j)
                            if x >= 1 << 63:
                                x -= 1 << 64
                            vals.append(x)
                    else:
                        vals.append(int(v))
            return np.asarray(vals, dtype=np.int64)
    return []


def decode_example(payload: bytes) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for field, features_buf in _iter_fields(payload):
        if field != 1:
            continue
        for f, entry in _iter_fields(features_buf):
            if f != 1:
                continue
            name = value = None
            for ef, ev in _iter_fields(entry):
                if ef == 1:
                    name = ev.decode()
                elif ef == 2:
                    value = _decode_feature(ev)
            if name is not None:
                out[name] = value
    return out


# ---------------------------------------------------------------------------
# Batch <-> examples
# ---------------------------------------------------------------------------

def batch_to_examples(batch: Dict[str, np.ndarray]) -> List[bytes]:
    names = list(batch)
    n = len(next(iter(batch.values()))) if batch else 0
    out = []
    for i in range(n):
        feats = {}
        for k in names:
            v = batch[k][i]
            if isinstance(v, np.ndarray):
                feats[k] = v.reshape(-1).tolist()
            else:
                feats[k] = [v]
        out.append(encode_example(feats))
    return out


def examples_to_batch(examples: List[Dict[str, Any]]) -> Dict[str, Any]:
    if not examples:
        return {}
    names = list(examples[0])
    out: Dict[str, Any] = {}
    for k in names:
        vals = [ex.get(k) for ex in examples]
        # Scalars unwrap; vectors stay as arrays (object column).
        if all(v is not None and len(v) == 1 for v in vals):
            first = vals[0]
            if isinstance(first, list):  # bytes list
                out[k] = [v[0] for v in vals]
            else:
                out[k] = np.asarray([v[0] for v in vals])
        else:
            out[k] = [np.asarray(v) for v in vals]
    return out
