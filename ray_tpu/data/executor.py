"""Streaming executor: logical plan → pipelined remote tasks.

Capability-equivalent to the reference's streaming execution
(reference: python/ray/data/_internal/execution/streaming_executor.py:57
StreamingExecutor and operators/ — TaskPoolMapOperator submitting one
remote task per block bundle :64, ActorPoolMapOperator for stateful UDFs,
bounded in-flight for backpressure): blocks flow through operator stages
as ObjectRefs; each map stage keeps at most `max_in_flight` tasks
outstanding; stateful (class) UDFs run on a reusable actor pool.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import get as ray_get, put as ray_put, remote, wait as ray_wait
from ..core.object_ref import ObjectRef
from .block import BlockAccessor, concat_blocks, split_block
from .plan import (
    FromBlocks,
    Limit,
    LogicalOp,
    MapLike,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    Union,
    _MapSpec,
)

# ---------------------------------------------------------------------------
# Remote transforms
# ---------------------------------------------------------------------------

def _apply_specs(block, specs: List[_MapSpec], fns: List[Any]):
    import pyarrow as pa

    for spec, fn in zip(specs, fns):
        acc = BlockAccessor.for_block(block)
        if spec.kind == "batches":
            if spec.batch_size and acc.num_rows() > spec.batch_size:
                outs = []
                for i in range(0, acc.num_rows(), spec.batch_size):
                    sub = acc.slice(i, min(i + spec.batch_size,
                                           acc.num_rows()))
                    batch = BlockAccessor.for_block(sub).to_batch(
                        spec.batch_format)
                    outs.append(BlockAccessor.for_block(fn(batch)).block)
                block = concat_blocks(outs)
            else:
                batch = acc.to_batch(spec.batch_format)
                block = BlockAccessor.for_block(fn(batch)).block
        elif spec.kind == "rows":
            rows = [fn(r) for r in acc.iter_rows()]
            block = BlockAccessor.for_block(rows).block
        elif spec.kind == "filter":
            rows = [r for r in acc.iter_rows() if fn(r)]
            block = (BlockAccessor.for_block(rows).block
                     if rows else acc.block.slice(0, 0))
        elif spec.kind == "flat":
            rows = [o for r in acc.iter_rows() for o in fn(r)]
            block = BlockAccessor.for_block(rows).block
        else:
            raise ValueError(spec.kind)
    return block


def _build_fns(specs: List[_MapSpec]) -> List[Any]:
    fns = []
    for spec in specs:
        fn = spec.fn
        if isinstance(fn, type):  # class UDF → construct once
            fn_obj = fn(*spec.fn_constructor_args,
                        **spec.fn_constructor_kwargs)
            fns.append(fn_obj)
        else:
            fns.append(fn)
    return fns


@remote
def _map_block_task(block, specs: List[_MapSpec]):
    return _apply_specs(block, specs, _build_fns(specs))


@remote
def _read_task(fn):
    block = fn()
    return BlockAccessor.for_block(block).block


class _MapWorker:
    """Actor-pool worker: constructs class UDFs once, reuses across blocks
    (reference: ActorPoolMapOperator)."""

    def __init__(self, specs: List[_MapSpec]):
        self.specs = specs
        self.fns = _build_fns(specs)

    def apply(self, block):
        return _apply_specs(block, self.specs, self.fns)


# ---------------------------------------------------------------------------
# Streaming stages
# ---------------------------------------------------------------------------

def _ref_nbytes(ref: ObjectRef) -> Optional[int]:
    """Size of a completed block, or None if not (yet) locally known."""
    try:
        from ..core.runtime import global_runtime_or_none

        rt = global_runtime_or_none()
        if rt is None:
            return None
        return rt.store.nbytes_if_exists(ref.id())
    except Exception:  # noqa: BLE001 — costing must never break the run
        return None


class _ByteWindow:
    """Adaptive in-flight window: counts TASKS until block sizes are
    observed, then bounds the window so outstanding output bytes stay
    near the stage's byte budget (reference:
    streaming_executor_state.py:525 select_operator_to_run dispatching
    under object-store budgets + backpressure_policy/). A pipeline of
    1 MiB blocks keeps the full task window; a pipeline of 512 MiB
    blocks shrinks toward one-in-flight."""

    def __init__(self, budget_bytes: int, max_tasks: int):
        self.budget = max(1, budget_bytes)
        self.max_tasks = max(1, max_tasks)
        self._avg: Optional[float] = None

    def observe(self, ref: ObjectRef) -> None:
        n = _ref_nbytes(ref)
        if n is None or n <= 0:
            return
        self._avg = (float(n) if self._avg is None
                     else 0.8 * self._avg + 0.2 * n)

    def limit(self) -> int:
        if not self._avg:
            return self.max_tasks
        return max(1, min(self.max_tasks,
                          int(self.budget // max(1.0, self._avg))))


def _stage_read(op: Read, max_in_flight: int,
                budget_bytes: int) -> Iterator[ObjectRef]:
    window: deque = deque()
    bw = _ByteWindow(budget_bytes, max_in_flight)
    tasks = iter(op.read_tasks)
    while True:
        while len(window) < bw.limit():
            fn = next(tasks, None)
            if fn is None:
                break
            window.append(_read_task.remote(ray_put(fn)))
            # Probe the window head while filling: the first completed
            # block's size shrinks the limit BEFORE the cold-start
            # flood finishes submitting a full task-count window.
            bw.observe(window[0])
        if not window:
            return
        ref = window.popleft()
        bw.observe(ref)
        yield ref


def _stage_map_tasks(op: MapLike, upstream: Iterator[ObjectRef],
                     max_in_flight: int,
                     budget_bytes: int) -> Iterator[ObjectRef]:
    window: deque = deque()
    specs_ref = ray_put(op.specs)
    opts: Dict[str, Any] = {"num_cpus": op.num_cpus}
    if op.num_tpus:
        opts["num_tpus"] = op.num_tpus
    task = _map_block_task.options(**opts)
    if op.concurrency is not None and not isinstance(op.concurrency, int):
        raise ValueError(
            "tuple concurrency bounds an autoscaling ACTOR pool and "
            "requires a class UDF (or compute='actors'); task-based "
            "maps take an int concurrency")
    bw = _ByteWindow(budget_bytes, op.concurrency or max_in_flight)
    for ref in upstream:
        window.append(task.remote(ref, specs_ref))
        bw.observe(window[0])
        while len(window) >= bw.limit():
            out = window.popleft()
            bw.observe(out)
            yield out
    while window:
        yield window.popleft()


def _pool_bounds(concurrency) -> Tuple[int, int]:
    """(min, max) pool size from the user's concurrency:
    int → fixed pool, (lo, hi) → autoscaling pool
    (reference: ActorPoolStrategy(min_size, max_size) /
    actor_pool_map_operator.py autoscaling)."""
    if concurrency is None:
        return 2, 2
    if isinstance(concurrency, int):
        return concurrency, concurrency
    lo, hi = concurrency
    if lo < 1 or hi < lo:
        raise ValueError(
            f"concurrency bounds must satisfy 1 <= min <= max, "
            f"got {concurrency}")
    return int(lo), int(hi)


def _stage_map_actors(op: MapLike, upstream: Iterator[ObjectRef],
                      max_in_flight: int,
                      budget_bytes: int) -> Iterator[ObjectRef]:
    from .. import kill as ray_kill

    lo, hi = _pool_bounds(op.concurrency)
    Worker = remote(num_cpus=op.num_cpus,
                    num_tpus=op.num_tpus or None)(_MapWorker)
    actors = [Worker.remote(op.specs) for _ in range(lo)]
    # In-flight calls per actor (least-loaded dispatch beats blind
    # round-robin when block costs vary).
    load = [0] * len(actors)
    window: deque = deque()  # (ref, actor_index)
    # Dispatched results not yet known complete. Pruned as they finish
    # so this NEVER pins completed blocks (that would defeat the byte
    # budget); what remains at teardown is what the drain must await.
    issued: List[ObjectRef] = []
    bw = _ByteWindow(budget_bytes, max_in_flight)
    try:
        def can_grow() -> bool:
            # A new actor must not consume the cluster's LAST cpu:
            # upstream read/map tasks still need somewhere to run, and
            # a pool holding every CPU deadlocks the very pipeline it
            # serves.
            if len(actors) >= hi:
                return False
            if not op.num_cpus:
                return True
            try:
                from ..core.runtime import global_runtime

                avail = global_runtime().available_resources()
                return avail.get("CPU", 0.0) - op.num_cpus >= 1.0
            except Exception:  # noqa: BLE001 — can't tell: don't grow
                return False

        def dispatch(ref):
            i = min(range(len(actors)), key=lambda j: load[j])
            # Queue depth beyond one call per actor = demand the pool
            # can't absorb → scale up toward max (reference:
            # actor_pool_map_operator autoscaling on queued inputs).
            if load[i] >= 1 and can_grow():
                actors.append(Worker.remote(op.specs))
                load.append(0)
                i = len(actors) - 1
            load[i] += 1
            out = actor_apply(actors[i], ref)
            issued.append(out)
            window.append((out, i))
            if len(issued) > 2 * bw.max_tasks:
                done, pending = ray_wait(
                    issued, num_returns=len(issued), timeout=0)
                issued[:] = pending

        def actor_apply(actor, ref):
            return actor.apply.remote(ref)

        for ref in upstream:
            dispatch(ref)
            while len(window) >= bw.limit():
                out, i = window.popleft()
                load[i] -= 1
                bw.observe(out)
                yield out
        while window:
            out, i = window.popleft()
            load[i] -= 1
            yield out
    finally:
        # Drain before teardown: downstream stages may not have read
        # the yielded futures yet — killing an actor mid-call turns
        # them into ActorDiedError. Only not-yet-complete calls remain
        # in `issued` (pruned above).
        try:
            if issued:
                ray_wait(issued, num_returns=len(issued), timeout=60)
        except Exception:  # noqa: BLE001 — best-effort drain
            pass
        for a in actors:
            try:
                ray_kill(a)
            except Exception:  # noqa: BLE001
                pass


def _stage_limit(op: Limit, upstream: Iterator[ObjectRef]
                 ) -> Iterator[ObjectRef]:
    remaining = op.n
    for ref in upstream:
        if remaining <= 0:
            return
        block = ray_get(ref)
        rows = BlockAccessor.for_block(block).num_rows()
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield ray_put(block.slice(0, remaining))
            remaining = 0
            return


# ---------------------------------------------------------------------------
# Push-based shuffle (reference: data/_internal/push_based_shuffle.py —
# two stages: map tasks partition each block and push pieces into the
# object plane; merge tasks combine one output partition each. Only
# refs transit the driver.)
# ---------------------------------------------------------------------------

@remote
def _shuffle_map(block, n_out: int, mode: str, arg, seed):
    """Split one block into n_out pieces → list of piece refs (None for
    empty pieces). mode: 'random' (arg unused), 'range' (arg =
    (key, boundaries, descending)), 'rr' (round-robin contiguous)."""
    from .. import put as ray_put_

    acc = BlockAccessor.for_block(block)
    t = acc.block
    n_rows = t.num_rows
    if n_rows == 0:
        return [None] * n_out
    if mode == "random":
        rng = np.random.RandomState(seed)
        assign = rng.randint(0, n_out, size=n_rows)
        pieces = [t.take(np.nonzero(assign == i)[0])
                  for i in range(n_out)]
    elif mode == "range":
        key, boundaries, descending = arg
        col = t.column(key).to_numpy(zero_copy_only=False)
        assign = np.searchsorted(np.asarray(boundaries), col,
                                 side="right")
        if descending:
            assign = (n_out - 1) - assign
        pieces = [t.take(np.nonzero(assign == i)[0])
                  for i in range(n_out)]
    else:  # "rr": contiguous split for repartition
        per = max(1, -(-n_rows // n_out))
        pieces = [t.slice(i * per, min(per, n_rows - i * per))
                  for i in range(n_out) if i * per < n_rows]
        pieces += [t.slice(0, 0)] * (n_out - len(pieces))
    return [ray_put_(p) if p.num_rows else None for p in pieces]


@remote
def _shuffle_merge(piece_refs, mode: str, arg, seed):
    """Combine one output partition's pieces into a block."""
    from .. import get as ray_get_

    t = concat_blocks([ray_get_(p) for p in piece_refs])
    if mode == "random":
        rng = np.random.RandomState(seed)
        return t.take(rng.permutation(t.num_rows))
    if mode == "range":
        key, _, descending = arg
        order = "descending" if descending else "ascending"
        return t.sort_by([(key, order)])
    return t


@remote
def _sample_bounds(block, key: str, n_samples: int, seed):
    acc = BlockAccessor.for_block(block)
    t = acc.block
    if t.num_rows == 0:
        return np.array([])
    col = t.column(key).to_numpy(zero_copy_only=False)
    rng = np.random.RandomState(seed)
    k = min(n_samples, len(col))
    return col[rng.choice(len(col), size=k, replace=False)]


def _push_shuffle(upstream: Iterator[ObjectRef], n_out: int, mode: str,
                  arg, seed) -> Iterator[ObjectRef]:
    map_refs = [_shuffle_map.remote(ref, n_out, mode, arg,
                                    None if seed is None else seed + i)
                for i, ref in enumerate(upstream)]
    parts: List[List[ObjectRef]] = [[] for _ in range(n_out)]
    for ref in map_refs:
        for i, piece in enumerate(ray_get(ref)):
            if piece is not None:
                parts[i].append(piece)
    merge_refs = [
        _shuffle_merge.remote(part, mode, arg,
                              None if seed is None else seed + 7919 * i)
        for i, part in enumerate(parts) if part]
    for ref in merge_refs:
        yield ref


def _stage_repartition(op: Repartition, upstream: Iterator[ObjectRef]
                       ) -> Iterator[ObjectRef]:
    yield from _push_shuffle(upstream, op.n, "rr", None, None)


def _stage_shuffle(op: RandomShuffle, upstream: Iterator[ObjectRef]
                   ) -> Iterator[ObjectRef]:
    refs = list(upstream)
    if not refs:
        return
    n_out = max(1, len(refs))
    # seed None stays None end-to-end → OS entropy per map task
    # (an unseeded shuffle must differ across runs).
    yield from _push_shuffle(iter(refs), n_out, "random", None, op.seed)


def _stage_sort(op: Sort, upstream: Iterator[ObjectRef]
                ) -> Iterator[ObjectRef]:
    """Sample-partitioned distributed sort: sample key values, compute
    range boundaries, range-partition in map tasks, sort each partition
    in merge tasks; partitions stream out in global key order."""
    refs = list(upstream)
    if not refs:
        return
    n_out = max(1, len(refs))
    if n_out == 1:
        block = ray_get(refs[0])
        order = "descending" if op.descending else "ascending"
        yield ray_put(block.sort_by([(op.key, order)]))
        return
    sample_arrays = [
        np.asarray(s) for s in ray_get(
            [_sample_bounds.remote(r, op.key, 32, i)
             for i, r in enumerate(refs)]) if len(s)]
    # All-empty upstream (e.g. filter dropped everything): no samples,
    # no boundaries — everything range-partitions to partition 0.
    samples = (np.concatenate(sample_arrays) if sample_arrays
               else np.array([]))
    # Positional boundaries from the sorted sample (works for string
    # keys too, where quantile interpolation wouldn't).
    if len(samples):
        s = np.sort(samples)
        idx = [int(len(s) * (i + 1) / n_out) for i in range(n_out - 1)]
        boundaries = [s[min(j, len(s) - 1)] for j in idx]
    else:
        boundaries = []
    arg = (op.key, boundaries, op.descending)
    yield from _push_shuffle(iter(refs), n_out, "range", arg, None)


class ExecStats:
    """Per-stage execution statistics for one dataset run
    (reference: data/_internal/stats.py DatasetStats → ds.stats()).

    Wall time per stage is measured pipelined — a stage's clock runs
    from its first block request to its last block yield, so stages
    overlap and the numbers say where the pipeline spent its time, not
    a serial breakdown.
    """

    def __init__(self):
        self.stages: List[dict] = []

    def _track(self, name: str, stream: Iterator) -> Iterator:
        import time as _time

        rec = {"stage": name, "blocks": 0, "wall_s": 0.0}
        self.stages.append(rec)

        def gen():
            t0 = _time.perf_counter()
            for ref in stream:
                rec["blocks"] += 1
                rec["wall_s"] = _time.perf_counter() - t0
                yield ref

        return gen()

    def summary(self) -> str:
        if not self.stages:
            return "No execution stats: dataset has not been executed."
        lines = []
        for rec in self.stages:
            lines.append(
                f"Stage {rec['stage']}: {rec['blocks']} blocks, "
                f"{rec['wall_s'] * 1000:.1f}ms wall (pipelined)")
        return "\n".join(lines)


def execute(root: LogicalOp, *, max_in_flight: Optional[int] = None,
            stats: Optional[ExecStats] = None) -> Iterator[ObjectRef]:
    """Compile the logical chain into a lazy pipelined iterator of block
    refs. Backpressure = bounded windows per map/read stage; the window
    defaults to DataContext.max_in_flight_tasks."""
    from .context import DataContext
    from .plan import optimize

    ctx = DataContext.get_current()
    if max_in_flight is None:
        max_in_flight = ctx.max_in_flight_tasks
    budget_bytes = ctx.max_in_flight_bytes
    if budget_bytes is None:
        budget_bytes = 256 << 20
        try:
            from ..core.runtime import global_runtime_or_none

            rt = global_runtime_or_none()
            if rt is not None and rt.shm is not None:
                budget_bytes = max(1 << 20, rt.shm.capacity() // 4)
        except Exception:  # noqa: BLE001 — default budget stands
            pass

    stream: Optional[Iterator[ObjectRef]] = None
    for op in optimize(root).chain():
        if isinstance(op, Read):
            stream = _stage_read(op, max_in_flight, budget_bytes)
        elif isinstance(op, FromBlocks):
            def _emit(blocks=op.blocks):
                for b in blocks:
                    yield b if isinstance(b, ObjectRef) else ray_put(b)
            stream = _emit()
        elif isinstance(op, MapLike):
            if op.compute == "actors" or (
                    op.compute is None and any(
                        isinstance(s.fn, type) for s in op.specs)):
                stream = _stage_map_actors(op, stream, max_in_flight,
                                           budget_bytes)
            else:
                stream = _stage_map_tasks(op, stream, max_in_flight,
                                          budget_bytes)
        elif isinstance(op, Limit):
            stream = _stage_limit(op, stream)
        elif isinstance(op, Repartition):
            stream = _stage_repartition(op, stream)
        elif isinstance(op, RandomShuffle):
            stream = _stage_shuffle(op, stream)
        elif isinstance(op, Sort):
            stream = _stage_sort(op, stream)
        elif isinstance(op, Union):
            def _union(main=stream, others=op.others):
                for r in main:
                    yield r
                for other in others:
                    # Branch stages land in the same stats object so
                    # union pipelines show the full breakdown.
                    for r in execute(other, max_in_flight=max_in_flight,
                                     stats=stats):
                        yield r
            stream = _union()
        else:
            raise ValueError(f"Unknown op {op}")
        if stats is not None:
            stream = stats._track(type(op).__name__, stream)
    assert stream is not None
    return stream
