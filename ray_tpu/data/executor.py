"""Streaming executor: logical plan → pipelined remote tasks.

Capability-equivalent to the reference's streaming execution
(reference: python/ray/data/_internal/execution/streaming_executor.py:57
StreamingExecutor and operators/ — TaskPoolMapOperator submitting one
remote task per block bundle :64, ActorPoolMapOperator for stateful UDFs,
bounded in-flight for backpressure): blocks flow through operator stages
as ObjectRefs; each map stage keeps at most `max_in_flight` tasks
outstanding; stateful (class) UDFs run on a reusable actor pool.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import get as ray_get, put as ray_put, remote, wait as ray_wait
from ..core.object_ref import ObjectRef
from .block import BlockAccessor, concat_blocks, split_block
from .plan import (
    FromBlocks,
    Limit,
    LogicalOp,
    MapLike,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    Union,
    _MapSpec,
)

# ---------------------------------------------------------------------------
# Remote transforms
# ---------------------------------------------------------------------------

def _apply_specs(block, specs: List[_MapSpec], fns: List[Any]):
    import pyarrow as pa

    for spec, fn in zip(specs, fns):
        acc = BlockAccessor.for_block(block)
        if spec.kind == "batches":
            if spec.batch_size and acc.num_rows() > spec.batch_size:
                outs = []
                for i in range(0, acc.num_rows(), spec.batch_size):
                    sub = acc.slice(i, min(i + spec.batch_size,
                                           acc.num_rows()))
                    batch = BlockAccessor.for_block(sub).to_batch(
                        spec.batch_format)
                    outs.append(BlockAccessor.for_block(fn(batch)).block)
                block = concat_blocks(outs)
            else:
                batch = acc.to_batch(spec.batch_format)
                block = BlockAccessor.for_block(fn(batch)).block
        elif spec.kind == "rows":
            rows = [fn(r) for r in acc.iter_rows()]
            block = BlockAccessor.for_block(rows).block
        elif spec.kind == "filter":
            rows = [r for r in acc.iter_rows() if fn(r)]
            block = (BlockAccessor.for_block(rows).block
                     if rows else acc.block.slice(0, 0))
        elif spec.kind == "flat":
            rows = [o for r in acc.iter_rows() for o in fn(r)]
            block = BlockAccessor.for_block(rows).block
        else:
            raise ValueError(spec.kind)
    return block


def _build_fns(specs: List[_MapSpec]) -> List[Any]:
    fns = []
    for spec in specs:
        fn = spec.fn
        if isinstance(fn, type):  # class UDF → construct once
            fn_obj = fn(*spec.fn_constructor_args,
                        **spec.fn_constructor_kwargs)
            fns.append(fn_obj)
        else:
            fns.append(fn)
    return fns


@remote
def _map_block_task(block, specs: List[_MapSpec]):
    return _apply_specs(block, specs, _build_fns(specs))


@remote
def _read_task(fn):
    block = fn()
    return BlockAccessor.for_block(block).block


class _MapWorker:
    """Actor-pool worker: constructs class UDFs once, reuses across blocks
    (reference: ActorPoolMapOperator)."""

    def __init__(self, specs: List[_MapSpec]):
        self.specs = specs
        self.fns = _build_fns(specs)

    def apply(self, block):
        return _apply_specs(block, self.specs, self.fns)


# ---------------------------------------------------------------------------
# Streaming stages
# ---------------------------------------------------------------------------

def _stage_read(op: Read, max_in_flight: int) -> Iterator[ObjectRef]:
    window: deque = deque()
    tasks = iter(op.read_tasks)
    try:
        while True:
            while len(window) < max_in_flight:
                fn = next(tasks, None)
                if fn is None:
                    break
                window.append(_read_task.remote(ray_put(fn)))
            if not window:
                return
            yield window.popleft()
    finally:
        pass


def _stage_map_tasks(op: MapLike, upstream: Iterator[ObjectRef],
                     max_in_flight: int) -> Iterator[ObjectRef]:
    window: deque = deque()
    specs_ref = ray_put(op.specs)
    opts: Dict[str, Any] = {"num_cpus": op.num_cpus}
    if op.num_tpus:
        opts["num_tpus"] = op.num_tpus
    task = _map_block_task.options(**opts)
    limit = op.concurrency or max_in_flight
    for ref in upstream:
        window.append(task.remote(ref, specs_ref))
        if len(window) >= limit:
            yield window.popleft()
    while window:
        yield window.popleft()


def _stage_map_actors(op: MapLike, upstream: Iterator[ObjectRef],
                      max_in_flight: int) -> Iterator[ObjectRef]:
    from .. import kill as ray_kill

    pool_size = op.concurrency or 2
    Worker = remote(num_cpus=op.num_cpus,
                    num_tpus=op.num_tpus or None)(_MapWorker)
    actors = [Worker.remote(op.specs) for _ in range(pool_size)]
    try:
        window: deque = deque()
        i = 0
        for ref in upstream:
            actor = actors[i % pool_size]
            i += 1
            window.append(actor.apply.remote(ref))
            if len(window) >= max_in_flight:
                yield window.popleft()
        while window:
            yield window.popleft()
    finally:
        for a in actors:
            try:
                ray_kill(a)
            except Exception:  # noqa: BLE001
                pass


def _stage_limit(op: Limit, upstream: Iterator[ObjectRef]
                 ) -> Iterator[ObjectRef]:
    remaining = op.n
    for ref in upstream:
        if remaining <= 0:
            return
        block = ray_get(ref)
        rows = BlockAccessor.for_block(block).num_rows()
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield ray_put(block.slice(0, remaining))
            remaining = 0
            return


# ---------------------------------------------------------------------------
# Push-based shuffle (reference: data/_internal/push_based_shuffle.py —
# two stages: map tasks partition each block and push pieces into the
# object plane; merge tasks combine one output partition each. Only
# refs transit the driver.)
# ---------------------------------------------------------------------------

@remote
def _shuffle_map(block, n_out: int, mode: str, arg, seed):
    """Split one block into n_out pieces → list of piece refs (None for
    empty pieces). mode: 'random' (arg unused), 'range' (arg =
    (key, boundaries, descending)), 'rr' (round-robin contiguous)."""
    from .. import put as ray_put_

    acc = BlockAccessor.for_block(block)
    t = acc.block
    n_rows = t.num_rows
    if n_rows == 0:
        return [None] * n_out
    if mode == "random":
        rng = np.random.RandomState(seed)
        assign = rng.randint(0, n_out, size=n_rows)
        pieces = [t.take(np.nonzero(assign == i)[0])
                  for i in range(n_out)]
    elif mode == "range":
        key, boundaries, descending = arg
        col = t.column(key).to_numpy(zero_copy_only=False)
        assign = np.searchsorted(np.asarray(boundaries), col,
                                 side="right")
        if descending:
            assign = (n_out - 1) - assign
        pieces = [t.take(np.nonzero(assign == i)[0])
                  for i in range(n_out)]
    else:  # "rr": contiguous split for repartition
        per = max(1, -(-n_rows // n_out))
        pieces = [t.slice(i * per, min(per, n_rows - i * per))
                  for i in range(n_out) if i * per < n_rows]
        pieces += [t.slice(0, 0)] * (n_out - len(pieces))
    return [ray_put_(p) if p.num_rows else None for p in pieces]


@remote
def _shuffle_merge(piece_refs, mode: str, arg, seed):
    """Combine one output partition's pieces into a block."""
    from .. import get as ray_get_

    t = concat_blocks([ray_get_(p) for p in piece_refs])
    if mode == "random":
        rng = np.random.RandomState(seed)
        return t.take(rng.permutation(t.num_rows))
    if mode == "range":
        key, _, descending = arg
        order = "descending" if descending else "ascending"
        return t.sort_by([(key, order)])
    return t


@remote
def _sample_bounds(block, key: str, n_samples: int, seed):
    acc = BlockAccessor.for_block(block)
    t = acc.block
    if t.num_rows == 0:
        return np.array([])
    col = t.column(key).to_numpy(zero_copy_only=False)
    rng = np.random.RandomState(seed)
    k = min(n_samples, len(col))
    return col[rng.choice(len(col), size=k, replace=False)]


def _push_shuffle(upstream: Iterator[ObjectRef], n_out: int, mode: str,
                  arg, seed) -> Iterator[ObjectRef]:
    map_refs = [_shuffle_map.remote(ref, n_out, mode, arg,
                                    None if seed is None else seed + i)
                for i, ref in enumerate(upstream)]
    parts: List[List[ObjectRef]] = [[] for _ in range(n_out)]
    for ref in map_refs:
        for i, piece in enumerate(ray_get(ref)):
            if piece is not None:
                parts[i].append(piece)
    merge_refs = [
        _shuffle_merge.remote(part, mode, arg,
                              None if seed is None else seed + 7919 * i)
        for i, part in enumerate(parts) if part]
    for ref in merge_refs:
        yield ref


def _stage_repartition(op: Repartition, upstream: Iterator[ObjectRef]
                       ) -> Iterator[ObjectRef]:
    yield from _push_shuffle(upstream, op.n, "rr", None, None)


def _stage_shuffle(op: RandomShuffle, upstream: Iterator[ObjectRef]
                   ) -> Iterator[ObjectRef]:
    refs = list(upstream)
    if not refs:
        return
    n_out = max(1, len(refs))
    # seed None stays None end-to-end → OS entropy per map task
    # (an unseeded shuffle must differ across runs).
    yield from _push_shuffle(iter(refs), n_out, "random", None, op.seed)


def _stage_sort(op: Sort, upstream: Iterator[ObjectRef]
                ) -> Iterator[ObjectRef]:
    """Sample-partitioned distributed sort: sample key values, compute
    range boundaries, range-partition in map tasks, sort each partition
    in merge tasks; partitions stream out in global key order."""
    refs = list(upstream)
    if not refs:
        return
    n_out = max(1, len(refs))
    if n_out == 1:
        block = ray_get(refs[0])
        order = "descending" if op.descending else "ascending"
        yield ray_put(block.sort_by([(op.key, order)]))
        return
    sample_arrays = [
        np.asarray(s) for s in ray_get(
            [_sample_bounds.remote(r, op.key, 32, i)
             for i, r in enumerate(refs)]) if len(s)]
    # All-empty upstream (e.g. filter dropped everything): no samples,
    # no boundaries — everything range-partitions to partition 0.
    samples = (np.concatenate(sample_arrays) if sample_arrays
               else np.array([]))
    # Positional boundaries from the sorted sample (works for string
    # keys too, where quantile interpolation wouldn't).
    if len(samples):
        s = np.sort(samples)
        idx = [int(len(s) * (i + 1) / n_out) for i in range(n_out - 1)]
        boundaries = [s[min(j, len(s) - 1)] for j in idx]
    else:
        boundaries = []
    arg = (op.key, boundaries, op.descending)
    yield from _push_shuffle(iter(refs), n_out, "range", arg, None)


class ExecStats:
    """Per-stage execution statistics for one dataset run
    (reference: data/_internal/stats.py DatasetStats → ds.stats()).

    Wall time per stage is measured pipelined — a stage's clock runs
    from its first block request to its last block yield, so stages
    overlap and the numbers say where the pipeline spent its time, not
    a serial breakdown.
    """

    def __init__(self):
        self.stages: List[dict] = []

    def _track(self, name: str, stream: Iterator) -> Iterator:
        import time as _time

        rec = {"stage": name, "blocks": 0, "wall_s": 0.0}
        self.stages.append(rec)

        def gen():
            t0 = _time.perf_counter()
            for ref in stream:
                rec["blocks"] += 1
                rec["wall_s"] = _time.perf_counter() - t0
                yield ref

        return gen()

    def summary(self) -> str:
        if not self.stages:
            return "No execution stats: dataset has not been executed."
        lines = []
        for rec in self.stages:
            lines.append(
                f"Stage {rec['stage']}: {rec['blocks']} blocks, "
                f"{rec['wall_s'] * 1000:.1f}ms wall (pipelined)")
        return "\n".join(lines)


def execute(root: LogicalOp, *, max_in_flight: Optional[int] = None,
            stats: Optional[ExecStats] = None) -> Iterator[ObjectRef]:
    """Compile the logical chain into a lazy pipelined iterator of block
    refs. Backpressure = bounded windows per map/read stage; the window
    defaults to DataContext.max_in_flight_tasks."""
    from .context import DataContext
    from .plan import optimize

    if max_in_flight is None:
        max_in_flight = DataContext.get_current().max_in_flight_tasks

    stream: Optional[Iterator[ObjectRef]] = None
    for op in optimize(root).chain():
        if isinstance(op, Read):
            stream = _stage_read(op, max_in_flight)
        elif isinstance(op, FromBlocks):
            def _emit(blocks=op.blocks):
                for b in blocks:
                    yield b if isinstance(b, ObjectRef) else ray_put(b)
            stream = _emit()
        elif isinstance(op, MapLike):
            if op.compute == "actors" or (
                    op.compute is None and any(
                        isinstance(s.fn, type) for s in op.specs)):
                stream = _stage_map_actors(op, stream, max_in_flight)
            else:
                stream = _stage_map_tasks(op, stream, max_in_flight)
        elif isinstance(op, Limit):
            stream = _stage_limit(op, stream)
        elif isinstance(op, Repartition):
            stream = _stage_repartition(op, stream)
        elif isinstance(op, RandomShuffle):
            stream = _stage_shuffle(op, stream)
        elif isinstance(op, Sort):
            stream = _stage_sort(op, stream)
        elif isinstance(op, Union):
            def _union(main=stream, others=op.others):
                for r in main:
                    yield r
                for other in others:
                    # Branch stages land in the same stats object so
                    # union pipelines show the full breakdown.
                    for r in execute(other, max_in_flight=max_in_flight,
                                     stats=stats):
                        yield r
            stream = _union()
        else:
            raise ValueError(f"Unknown op {op}")
        if stats is not None:
            stream = stats._track(type(op).__name__, stream)
    assert stream is not None
    return stream
