"""Streaming executor: logical plan → pipelined remote tasks.

Capability-equivalent to the reference's streaming execution
(reference: python/ray/data/_internal/execution/streaming_executor.py:57
StreamingExecutor and operators/ — TaskPoolMapOperator submitting one
remote task per block bundle :64, ActorPoolMapOperator for stateful UDFs,
bounded in-flight for backpressure): blocks flow through operator stages
as ObjectRefs; each map stage keeps at most `max_in_flight` tasks
outstanding; stateful (class) UDFs run on a reusable actor pool.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import get as ray_get, put as ray_put, remote, wait as ray_wait
from ..core.object_ref import ObjectRef
from .block import BlockAccessor, concat_blocks, split_block
from .plan import (
    FromBlocks,
    Limit,
    LogicalOp,
    MapLike,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    Union,
    _MapSpec,
)

DEFAULT_MAX_IN_FLIGHT = 8


# ---------------------------------------------------------------------------
# Remote transforms
# ---------------------------------------------------------------------------

def _apply_specs(block, specs: List[_MapSpec], fns: List[Any]):
    import pyarrow as pa

    for spec, fn in zip(specs, fns):
        acc = BlockAccessor.for_block(block)
        if spec.kind == "batches":
            if spec.batch_size and acc.num_rows() > spec.batch_size:
                outs = []
                for i in range(0, acc.num_rows(), spec.batch_size):
                    sub = acc.slice(i, min(i + spec.batch_size,
                                           acc.num_rows()))
                    batch = BlockAccessor.for_block(sub).to_batch(
                        spec.batch_format)
                    outs.append(BlockAccessor.for_block(fn(batch)).block)
                block = concat_blocks(outs)
            else:
                batch = acc.to_batch(spec.batch_format)
                block = BlockAccessor.for_block(fn(batch)).block
        elif spec.kind == "rows":
            rows = [fn(r) for r in acc.iter_rows()]
            block = BlockAccessor.for_block(rows).block
        elif spec.kind == "filter":
            rows = [r for r in acc.iter_rows() if fn(r)]
            block = (BlockAccessor.for_block(rows).block
                     if rows else acc.block.slice(0, 0))
        elif spec.kind == "flat":
            rows = [o for r in acc.iter_rows() for o in fn(r)]
            block = BlockAccessor.for_block(rows).block
        else:
            raise ValueError(spec.kind)
    return block


def _build_fns(specs: List[_MapSpec]) -> List[Any]:
    fns = []
    for spec in specs:
        fn = spec.fn
        if isinstance(fn, type):  # class UDF → construct once
            fn_obj = fn(*spec.fn_constructor_args,
                        **spec.fn_constructor_kwargs)
            fns.append(fn_obj)
        else:
            fns.append(fn)
    return fns


@remote
def _map_block_task(block, specs: List[_MapSpec]):
    return _apply_specs(block, specs, _build_fns(specs))


@remote
def _read_task(fn):
    block = fn()
    return BlockAccessor.for_block(block).block


class _MapWorker:
    """Actor-pool worker: constructs class UDFs once, reuses across blocks
    (reference: ActorPoolMapOperator)."""

    def __init__(self, specs: List[_MapSpec]):
        self.specs = specs
        self.fns = _build_fns(specs)

    def apply(self, block):
        return _apply_specs(block, self.specs, self.fns)


# ---------------------------------------------------------------------------
# Streaming stages
# ---------------------------------------------------------------------------

def _stage_read(op: Read, max_in_flight: int) -> Iterator[ObjectRef]:
    window: deque = deque()
    tasks = iter(op.read_tasks)
    try:
        while True:
            while len(window) < max_in_flight:
                fn = next(tasks, None)
                if fn is None:
                    break
                window.append(_read_task.remote(ray_put(fn)))
            if not window:
                return
            yield window.popleft()
    finally:
        pass


def _stage_map_tasks(op: MapLike, upstream: Iterator[ObjectRef],
                     max_in_flight: int) -> Iterator[ObjectRef]:
    window: deque = deque()
    specs_ref = ray_put(op.specs)
    opts: Dict[str, Any] = {"num_cpus": op.num_cpus}
    if op.num_tpus:
        opts["num_tpus"] = op.num_tpus
    task = _map_block_task.options(**opts)
    limit = op.concurrency or max_in_flight
    for ref in upstream:
        window.append(task.remote(ref, specs_ref))
        if len(window) >= limit:
            yield window.popleft()
    while window:
        yield window.popleft()


def _stage_map_actors(op: MapLike, upstream: Iterator[ObjectRef],
                      max_in_flight: int) -> Iterator[ObjectRef]:
    from .. import kill as ray_kill

    pool_size = op.concurrency or 2
    Worker = remote(num_cpus=op.num_cpus,
                    num_tpus=op.num_tpus or None)(_MapWorker)
    actors = [Worker.remote(op.specs) for _ in range(pool_size)]
    try:
        window: deque = deque()
        i = 0
        for ref in upstream:
            actor = actors[i % pool_size]
            i += 1
            window.append(actor.apply.remote(ref))
            if len(window) >= max_in_flight:
                yield window.popleft()
        while window:
            yield window.popleft()
    finally:
        for a in actors:
            try:
                ray_kill(a)
            except Exception:  # noqa: BLE001
                pass


def _stage_limit(op: Limit, upstream: Iterator[ObjectRef]
                 ) -> Iterator[ObjectRef]:
    remaining = op.n
    for ref in upstream:
        if remaining <= 0:
            return
        block = ray_get(ref)
        rows = BlockAccessor.for_block(block).num_rows()
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield ray_put(block.slice(0, remaining))
            remaining = 0
            return


def _stage_repartition(op: Repartition, upstream: Iterator[ObjectRef]
                       ) -> Iterator[ObjectRef]:
    blocks = [ray_get(r) for r in upstream]
    merged = concat_blocks(blocks) if blocks else None
    if merged is None:
        return
    rows = merged.num_rows
    per = max(1, rows // op.n)
    start = 0
    for i in range(op.n):
        end = rows if i == op.n - 1 else min(start + per, rows)
        if start >= end and i < op.n - 1:
            continue
        yield ray_put(merged.slice(start, end - start))
        start = end


def _stage_shuffle(op: RandomShuffle, upstream: Iterator[ObjectRef]
                   ) -> Iterator[ObjectRef]:
    rng = np.random.RandomState(op.seed)
    blocks = [ray_get(r) for r in upstream]
    if not blocks:
        return
    merged = concat_blocks(blocks)
    perm = rng.permutation(merged.num_rows)
    shuffled = merged.take(perm)
    for piece in split_block(shuffled, max(1, len(blocks))):
        yield ray_put(piece)


def _stage_sort(op: Sort, upstream: Iterator[ObjectRef]
                ) -> Iterator[ObjectRef]:
    blocks = [ray_get(r) for r in upstream]
    if not blocks:
        return
    merged = concat_blocks(blocks)
    order = "descending" if op.descending else "ascending"
    yield ray_put(merged.sort_by([(op.key, order)]))


def execute(root: LogicalOp, *, max_in_flight: int = DEFAULT_MAX_IN_FLIGHT
            ) -> Iterator[ObjectRef]:
    """Compile the logical chain into a lazy pipelined iterator of block
    refs. Backpressure = bounded windows per map/read stage."""
    from .plan import optimize

    stream: Optional[Iterator[ObjectRef]] = None
    for op in optimize(root).chain():
        if isinstance(op, Read):
            stream = _stage_read(op, max_in_flight)
        elif isinstance(op, FromBlocks):
            def _emit(blocks=op.blocks):
                for b in blocks:
                    yield b if isinstance(b, ObjectRef) else ray_put(b)
            stream = _emit()
        elif isinstance(op, MapLike):
            if op.compute == "actors" or (
                    op.compute is None and any(
                        isinstance(s.fn, type) for s in op.specs)):
                stream = _stage_map_actors(op, stream, max_in_flight)
            else:
                stream = _stage_map_tasks(op, stream, max_in_flight)
        elif isinstance(op, Limit):
            stream = _stage_limit(op, stream)
        elif isinstance(op, Repartition):
            stream = _stage_repartition(op, stream)
        elif isinstance(op, RandomShuffle):
            stream = _stage_shuffle(op, stream)
        elif isinstance(op, Sort):
            stream = _stage_sort(op, stream)
        elif isinstance(op, Union):
            def _union(main=stream, others=op.others):
                for r in main:
                    yield r
                for other in others:
                    for r in execute(other, max_in_flight=max_in_flight):
                        yield r
            stream = _union()
        else:
            raise ValueError(f"Unknown op {op}")
    assert stream is not None
    return stream
