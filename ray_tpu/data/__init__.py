from .block import Block, BlockAccessor
from .dataset import Dataset
from .iterator import DataIterator
from .read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    write_parquet,
)

__all__ = [
    "Dataset", "DataIterator", "Block", "BlockAccessor",
    "from_items", "from_pandas", "from_numpy", "from_arrow", "range",
    "range_tensor", "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images", "write_parquet",
]
