from . import aggregate
from .block import Block, BlockAccessor
from .context import DataContext
from .dataset import Dataset
from .grouped_data import GroupedData
from .iterator import DataIterator
from .connectors import (
    BigQueryDatasource,
    DeltaDatasource,
    IcebergDatasource,
    MongoDatasource,
    read_avro,
    read_bigquery,
    read_clickhouse,
    read_delta,
    read_iceberg,
    read_mongo,
    read_snowflake,
    write_bigquery,
    write_mongo,
    write_sql,
)
from .read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
    write_csv,
    write_json,
    write_numpy,
    write_parquet,
    write_tfrecords,
)
from .datasource import (
    Datasink,
    Datasource,
    ReadTask,
    read_datasource,
    write_datasink,
)

__all__ = [
    "Dataset", "DataIterator", "Block", "BlockAccessor", "GroupedData",
    "DataContext",
    "aggregate",
    "from_items", "from_pandas", "from_numpy", "from_arrow",
    "from_torch", "from_huggingface", "range",
    "range_tensor", "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images", "read_tfrecords",
    "read_webdataset", "read_sql", "read_mongo", "read_bigquery",
    "read_iceberg", "read_delta", "read_clickhouse", "read_snowflake",
    "read_avro", "write_mongo", "write_bigquery", "write_sql",
    "MongoDatasource", "BigQueryDatasource", "IcebergDatasource",
    "DeltaDatasource",
    "write_parquet", "write_csv", "write_json", "write_numpy",
    "write_tfrecords",
    "Datasource", "Datasink", "ReadTask", "read_datasource",
    "write_datasink",
]
