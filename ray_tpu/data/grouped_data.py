"""GroupedData — distributed group-by aggregation.

Capability-equivalent to the reference's grouped data
(reference: python/ray/data/grouped_data.py — GroupedData.aggregate,
count/sum/min/max/mean/std, map_groups): blocks are hash-partitioned by
key in parallel remote tasks (each key lands wholly in one partition),
then each partition is aggregated with pyarrow group_by kernels (or a
user fn for map_groups) in its own remote task — a one-round push-style
shuffle rather than the reference's sort-based two-stage shuffle.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import get as ray_get, put as ray_put, remote
from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import BlockAccessor, concat_blocks

@remote
def _partition_block(block, key: str, n: int):
    """Hash-partition one block by key → list of n piece refs (None for
    empty pieces). Pieces go straight into the object plane so the
    driver only ever handles refs, not data."""
    from .. import put as ray_put_

    acc = BlockAccessor.for_block(block)
    t = acc.block
    if t.num_rows == 0:
        return [None] * n
    col = t.column(key).to_numpy(zero_copy_only=False)
    # Stable per-value hash (numpy value hash — not PYTHONHASHSEED'd).
    if col.dtype.kind in "iufb":
        idx = (col.astype(np.int64, copy=False) % n + n) % n
    else:
        import zlib

        idx = np.array([zlib.crc32(str(v).encode()) % n for v in col],
                       dtype=np.int64)
    out = []
    for i in range(n):
        piece = t.take(np.nonzero(idx == i)[0])
        out.append(ray_put_(piece) if piece.num_rows else None)
    return out


def _iter_key_groups(t, key: str):
    """Sort by key, yield (key_value, group_slice) per distinct key."""
    sorted_t = t.sort_by([(key, "ascending")])
    keys = sorted_t.column(key).to_numpy(zero_copy_only=False)
    bounds = np.nonzero(keys[1:] != keys[:-1])[0] + 1
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [len(keys)]])
    for s, e in zip(starts, ends):
        yield keys[s], sorted_t.slice(s, e - s)


@remote
def _agg_partition(pieces, key: Optional[str], aggs: List[AggregateFn]):
    """Aggregate one partition (given its piece refs). Fast path: every
    agg maps to a pyarrow group_by kernel; else generic accumulate."""
    from .. import get as ray_get_

    t = concat_blocks([ray_get_(p) for p in pieces])
    if t.num_rows == 0:
        return t.slice(0, 0)
    if key is None:
        raise ValueError("partition aggregation requires a key")
    if all(a.arrow_kernel for a in aggs):
        pairs, names = [], []
        for a in aggs:
            col = key if a.arrow_kernel == "count" else a.on
            if a.arrow_options is not None:
                pairs.append((col, a.arrow_kernel, a.arrow_options))
            else:
                pairs.append((col, a.arrow_kernel))
            names.append(a.name)
        out = t.group_by(key).aggregate(pairs)
        # pyarrow names result columns "<col>_<kernel>"; column order has
        # changed across versions — select by name, normalize to
        # [key, agg1, agg2, ...].
        import pyarrow as pa

        cols = [out.column(key)] + [
            out.column(f"{p[0]}_{p[1]}") for p in pairs]
        return pa.table(cols, names=[key] + names)
    # Generic path: split into per-key groups, run accumulate/finalize.
    rows = []
    for key_val, grp in _iter_key_groups(t, key):
        row: Dict[str, Any] = {key: key_val}
        for a in aggs:
            acc = a.accumulate_block(a.init(), grp)
            row[a.name] = a.finalize(acc)
        rows.append(row)
    return BlockAccessor.for_block(rows).block


@remote
def _map_groups_partition(pieces, key: str, fn, batch_format: str):
    from .. import get as ray_get_

    t = concat_blocks([ray_get_(p) for p in pieces])
    if t.num_rows == 0:
        return t.slice(0, 0)
    outs = []
    for _, grp in _iter_key_groups(t, key):
        batch = BlockAccessor.for_block(grp).to_batch(batch_format)
        outs.append(BlockAccessor.for_block(fn(batch)).block)
    return concat_blocks(outs)


class GroupedData:
    def __init__(self, dataset, key: str,
                 num_partitions: Optional[int] = None):
        from .context import DataContext

        self._ds = dataset
        self._key = key
        self._n = (num_partitions
                   or DataContext.get_current().groupby_num_partitions)

    def _partitions(self) -> List[List[Any]]:
        """Hash-shuffle the dataset's blocks → n lists of piece refs.
        Only refs transit the driver; piece data stays in the object
        plane until the per-partition aggregation task pulls it."""
        part_refs = [_partition_block.remote(ref, self._key, self._n)
                     for ref in self._ds._refs()]
        parts: List[List[Any]] = [[] for _ in range(self._n)]
        for ref in part_refs:
            for i, piece_ref in enumerate(ray_get(ref)):
                if piece_ref is not None:
                    parts[i].append(piece_ref)
        return parts

    def aggregate(self, *aggs: AggregateFn):
        from .dataset import Dataset
        from .plan import FromBlocks

        refs = [_agg_partition.remote(part, self._key, list(aggs))
                for part in self._partitions() if part]
        blocks = [b for b in ray_get(refs) if b.num_rows]
        merged = concat_blocks(blocks) if blocks else None
        if merged is None:
            import pyarrow as pa

            merged = pa.table({})
        merged = (merged.sort_by([(self._key, "ascending")])
                  if merged.num_rows else merged)
        out_ref = ray_put(merged)
        d = Dataset(FromBlocks([out_ref], "aggregate"))
        d._materialized = [out_ref]
        return d

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"):
        from .dataset import Dataset
        from .plan import FromBlocks

        refs = [_map_groups_partition.remote(part, self._key, fn,
                                             batch_format)
                for part in self._partitions() if part]
        d = Dataset(FromBlocks(refs, "map_groups"))
        d._materialized = refs
        return d

    # -- sugar ----------------------------------------------------------
    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof))
