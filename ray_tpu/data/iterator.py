"""DataIterator — batch iteration with TPU HBM prefetch.

Capability-equivalent to the reference's iterator
(reference: python/ray/data/iterator.py + block_batching/) plus the
TPU-first addition: `iter_batches(device_put=True)` double-buffers host
batches into HBM (jax.device_put with a lookahead queue) so the input
pipeline overlaps with the train step — the role the reference delegates
to torch DataLoader prefetch (train_loop_utils.py:116).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from .block import BlockAccessor, concat_blocks


class DataIterator:
    """Iterates batches from a stream of block refs."""

    def __init__(self, ref_iter_factory: Callable[[], Iterator]):
        self._factory = ref_iter_factory

    # -- plumbing ---------------------------------------------------------
    def _blocks(self) -> Iterator:
        from .. import get as ray_get

        for ref in self._factory():
            yield ray_get(ref)

    # -- public -----------------------------------------------------------
    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes: Optional[Dict[str, Any]] = None,
                           device: Optional[str] = None,
                           drop_last: bool = False) -> Iterator[Any]:
        """Batches as dicts of torch tensors (reference:
        iterator.py iter_torch_batches — numpy → torch conversion with
        optional per-column dtypes and target device)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                if v.dtype == object:
                    out[k] = list(v)  # strings/bytes stay python
                    continue
                arr = np.ascontiguousarray(v)
                if not arr.flags.writeable:
                    arr = arr.copy()  # torch refuses non-writable views
                t = torch.from_numpy(arr)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_batches(self, *, batch_size: Optional[int] = -1,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     device_put: bool = False,
                     sharding: Optional[Any] = None,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     drop_last: bool = False) -> Iterator[Any]:
        """Re-batch blocks to `batch_size` rows (-1 = the DataContext
        default). With device_put=True, batches are staged into device
        memory `prefetch_batches` ahead. local_shuffle_buffer_size
        enables windowed row shuffling (reference:
        iterator.py iter_batches local_shuffle_buffer_size — randomize
        training ingest without a full-dataset shuffle)."""
        if batch_size == -1:
            from .context import DataContext

            batch_size = DataContext.get_current().default_batch_size

        def blocks_maybe_shuffled():
            if not local_shuffle_buffer_size:
                yield from self._blocks()
                return
            # Sliding-buffer shuffle (reference semantics): keep
            # buffer_size rows resident; overflow rows are emitted in a
            # random order while the RETAINED rows are also randomly
            # chosen — so held-back rows mix with later arrivals across
            # window boundaries (not a disjoint-partition shuffle).
            rng = np.random.RandomState(local_shuffle_seed)
            buf: List = []
            rows = 0
            for block in self._blocks():
                if block.num_rows == 0:
                    continue
                buf.append(block)
                rows += block.num_rows
                if rows > local_shuffle_buffer_size:
                    merged = concat_blocks(buf)
                    perm = rng.permutation(merged.num_rows)
                    emit = merged.num_rows - local_shuffle_buffer_size
                    yield merged.take(perm[:emit])
                    buf = [merged.take(perm[emit:])]
                    rows = local_shuffle_buffer_size
            if buf:
                merged = concat_blocks(buf)
                yield merged.take(rng.permutation(merged.num_rows))

        def host_batches():
            carry: List = []
            carry_rows = 0
            for block in blocks_maybe_shuffled():
                if block.num_rows == 0:
                    continue
                if batch_size is None:
                    yield BlockAccessor.for_block(block).to_batch(
                        batch_format)
                    continue
                carry.append(block)
                carry_rows += block.num_rows
                while carry_rows >= batch_size:
                    merged = concat_blocks(carry)
                    head = merged.slice(0, batch_size)
                    rest = merged.slice(batch_size,
                                        merged.num_rows - batch_size)
                    yield BlockAccessor.for_block(head).to_batch(
                        batch_format)
                    carry = [rest] if rest.num_rows else []
                    carry_rows = rest.num_rows
            if carry_rows and not drop_last and batch_size is not None:
                merged = concat_blocks(carry)
                yield BlockAccessor.for_block(merged).to_batch(batch_format)

        if not device_put:
            yield from host_batches()
            return
        yield from _device_prefetch(
            host_batches(), prefetch_batches, sharding)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def materialize_blocks(self) -> List:
        return list(self._blocks())


def _device_prefetch(batches: Iterator, depth: int,
                     sharding) -> Iterator:
    """Stage host batches onto device(s) ahead of consumption."""
    import jax

    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    DONE = object()

    def producer():
        try:
            for batch in batches:
                if isinstance(batch, dict):
                    if sharding is not None:
                        dev = {k: jax.device_put(v, sharding)
                               for k, v in batch.items()}
                    else:
                        dev = {k: jax.device_put(v)
                               for k, v in batch.items()}
                else:
                    dev = (jax.device_put(batch, sharding)
                           if sharding is not None else jax.device_put(batch))
                q.put(dev)
        except BaseException as e:  # noqa: BLE001
            q.put(e)
        finally:
            q.put(DONE)

    t = threading.Thread(target=producer, daemon=True,
                         name="device-prefetch")
    t.start()
    while True:
        item = q.get()
        if item is DONE:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


class SplitIterator(DataIterator):
    """One consumer's view of a streaming split
    (reference: execution/operators/output_splitter.py — a single
    executor feeding N consumers; here a shared feeder thread + per-
    consumer bounded queues, equalized round-robin)."""

    def __init__(self, split_state: "_SplitState", index: int):
        self._state = split_state
        self._index = index
        super().__init__(self._ref_iter)

    def _ref_iter(self):
        return self._state.consume(self._index)


class _SplitState:
    def __init__(self, ref_iter: Iterator, n: int, equal: bool):
        self.n = n
        self.equal = equal
        self.queues = [queue.Queue(maxsize=4) for _ in range(n)]
        self.DONE = object()
        self._thread = threading.Thread(
            target=self._feed, args=(ref_iter,), daemon=True,
            name="streaming-split-feeder")
        self._started = False
        self._exhausted = [False] * n  # re-iteration returns empty, no hang
        self._lock = threading.Lock()

    def _ensure_started(self):
        with self._lock:
            if not self._started:
                self._started = True
                self._thread.start()

    def _feed(self, ref_iter):
        i = 0
        try:
            for ref in ref_iter:
                self.queues[i % self.n].put(ref)
                i += 1
        except BaseException as e:  # noqa: BLE001
            for q in self.queues:
                q.put(e)
        finally:
            for q in self.queues:
                q.put(self.DONE)

    def consume(self, index: int):
        with self._lock:
            if self._exhausted[index]:
                # A streaming split is single-pass (one shared execution);
                # a second epoch sees an empty stream rather than a hang.
                return
        self._ensure_started()
        q = self.queues[index]
        while True:
            item = q.get()
            if item is self.DONE:
                with self._lock:
                    self._exhausted[index] = True
                return
            if isinstance(item, BaseException):
                raise item
            yield item
