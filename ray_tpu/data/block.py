"""Blocks — the unit of distributed data.

Capability-equivalent to the reference's block layer
(reference: python/ray/data/_internal/arrow_block.py, pandas_block.py):
a Block is a pyarrow Table; BlockAccessor adapts tables/dicts/pandas and
formats batches (numpy/pandas/pyarrow/dict). TPU-first addition: numpy
batches are produced as contiguous arrays ready for jax.device_put.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
BatchFormat = str  # "numpy" | "pandas" | "pyarrow" | "dict"


def _to_table(data: Any) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        cols = {}
        for k, v in data.items():
            v = np.asarray(v)
            if v.ndim > 1:
                # tensor column → fixed-shape list array
                cols[k] = _tensor_to_arrow(v)
            else:
                cols[k] = pa.array(v)
        return pa.table(cols)
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, list):
        if not data:
            return pa.table({})
        if isinstance(data[0], dict):
            return pa.Table.from_pylist(data)
        return pa.table({"item": pa.array(data)})
    raise TypeError(f"Cannot convert {type(data)} to a Block")


def _tensor_to_arrow(arr: np.ndarray) -> pa.Array:
    flat = arr.reshape(len(arr), -1)
    inner = pa.list_(pa.from_numpy_dtype(arr.dtype), flat.shape[1])
    values = pa.array(flat.reshape(-1))
    storage = pa.FixedSizeListArray.from_arrays(values, flat.shape[1])
    meta = {"shape": list(arr.shape[1:])}
    return storage


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @classmethod
    def for_block(cls, data: Any) -> "BlockAccessor":
        return cls(_to_table(data))

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def to_batch(self, batch_format: BatchFormat = "numpy") -> Any:
        if batch_format in ("numpy", "dict"):
            out: Dict[str, np.ndarray] = {}
            for name in self.block.column_names:
                col = self.block.column(name)
                if pa.types.is_fixed_size_list(col.type):
                    width = col.type.list_size
                    flat = col.combine_chunks().flatten().to_numpy(
                        zero_copy_only=False)
                    out[name] = flat.reshape(self.block.num_rows, width)
                else:
                    out[name] = col.to_numpy(zero_copy_only=False)
            return out
        if batch_format == "pandas":
            return self.block.to_pandas()
        if batch_format == "pyarrow":
            return self.block
        raise ValueError(f"Unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for row in self.block.to_pylist():
            yield row


def concat_blocks(blocks: List[Block]) -> Block:
    tables = [b for b in blocks if b.num_rows > 0]
    if not tables:
        return blocks[0] if blocks else pa.table({})
    return pa.concat_tables(tables, promote_options="permissive")


def split_block(block: Block, n: int) -> List[Block]:
    rows = block.num_rows
    per = max(1, rows // n)
    out = []
    for i in range(0, rows, per):
        out.append(block.slice(i, min(per, rows - i)))
    return out
