"""Blocks — the unit of distributed data.

Capability-equivalent to the reference's block layer
(reference: python/ray/data/_internal/arrow_block.py, pandas_block.py):
a Block is a pyarrow Table; BlockAccessor adapts tables/dicts/pandas and
formats batches (numpy/pandas/pyarrow/dict). TPU-first addition: numpy
batches are produced as contiguous arrays ready for jax.device_put.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
BatchFormat = str  # "numpy" | "pandas" | "pyarrow" | "dict"


def _to_table(data: Any) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        cols = {}
        fields = []
        for k, v in data.items():
            if isinstance(v, list) and v and isinstance(
                    v[0], (bytes, bytearray)):
                # bytes must NOT round-trip through numpy ('S' dtype
                # strips trailing \x00s) — go straight to arrow binary.
                arr = pa.array(v, type=pa.binary())
                cols[k] = arr
                fields.append(pa.field(k, arr.type))
                continue
            v = np.asarray(v)
            if v.ndim > 1:
                arr, shape = _tensor_to_arrow(v)
                cols[k] = arr
                fields.append(pa.field(
                    k, arr.type,
                    metadata={b"tensor_shape": _shape_bytes(shape)}))
            else:
                if v.dtype.kind == "S":
                    arr = pa.array(
                        [bytes(x) for x in v.tolist()], type=pa.binary())
                else:
                    arr = pa.array(v)
                cols[k] = arr
                fields.append(pa.field(k, arr.type))
        return pa.Table.from_arrays(
            list(cols.values()), schema=pa.schema(fields))
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, list):
        if not data:
            return pa.table({})
        if isinstance(data[0], dict):
            keys = list(data[0].keys())
            if (any(isinstance(v, np.ndarray) for v in data[0].values())
                    and all(isinstance(d, dict) and set(d.keys()) == set(keys)
                            for d in data)):
                # ndarray-valued rows (e.g. images): go through the
                # column path so the tensor-extension encoding applies.
                try:
                    return _to_table({k: [d[k] for d in data]
                                      for k in keys})
                except (ValueError, pa.ArrowInvalid):
                    pass  # ragged shapes — fall through to pylist
            return pa.Table.from_pylist(data)
        return pa.table({"item": pa.array(data)})
    raise TypeError(f"Cannot convert {type(data)} to a Block")


def _shape_bytes(shape) -> bytes:
    return ",".join(str(int(s)) for s in shape).encode()


def _shape_from_bytes(b: bytes):
    return tuple(int(x) for x in b.decode().split(",") if x)


def _tensor_to_arrow(arr: np.ndarray):
    """N-d tensor column → fixed-size-list array + per-row shape (stored
    in the field metadata so to_batch can restore the original rank)."""
    flat = arr.reshape(len(arr), -1)
    values = pa.array(flat.reshape(-1))
    storage = pa.FixedSizeListArray.from_arrays(values, flat.shape[1])
    return storage, arr.shape[1:]


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @classmethod
    def for_block(cls, data: Any) -> "BlockAccessor":
        return cls(_to_table(data))

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def to_batch(self, batch_format: BatchFormat = "numpy") -> Any:
        if batch_format in ("numpy", "dict"):
            out: Dict[str, np.ndarray] = {}
            for i, name in enumerate(self.block.column_names):
                col = self.block.column(name)
                field = self.block.schema.field(i)
                if pa.types.is_fixed_size_list(col.type):
                    width = col.type.list_size
                    flat = col.combine_chunks().flatten().to_numpy(
                        zero_copy_only=False)
                    meta = field.metadata or {}
                    if b"tensor_shape" in meta:
                        shape = _shape_from_bytes(meta[b"tensor_shape"])
                        out[name] = flat.reshape(
                            (self.block.num_rows,) + shape)
                    else:
                        out[name] = flat.reshape(self.block.num_rows, width)
                elif pa.types.is_binary(col.type) or \
                        pa.types.is_large_binary(col.type):
                    out[name] = np.array(
                        col.to_pylist(), dtype=object)
                else:
                    out[name] = col.to_numpy(zero_copy_only=False)
            return out
        if batch_format == "pandas":
            return self.block.to_pandas()
        if batch_format == "pyarrow":
            return self.block
        raise ValueError(f"Unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for row in self.block.to_pylist():
            yield row


def concat_blocks(blocks: List[Block]) -> Block:
    tables = [b for b in blocks if b.num_rows > 0]
    if not tables:
        return blocks[0] if blocks else pa.table({})
    return pa.concat_tables(tables, promote_options="permissive")


def split_block(block: Block, n: int) -> List[Block]:
    rows = block.num_rows
    per = max(1, rows // n)
    out = []
    for i in range(0, rows, per):
        out.append(block.slice(i, min(per, rows - i)))
    return out
