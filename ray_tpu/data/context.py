"""DataContext — per-process execution configuration for Data.

Capability-equivalent of the reference's DataContext
(reference: python/ray/data/context.py — a get_current() singleton of
execution toggles read by the planner/executor): bounds the streaming
executor's in-flight window, default batch sizes, and shuffle
parallelism without threading arguments through every operator.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import ClassVar, Optional


@dataclass
class DataContext:
    #: Max in-flight tasks per streaming map/read stage (backpressure).
    max_in_flight_tasks: int = 8
    #: Byte budget for a stage's in-flight outputs (backpressure in
    #: BYTES, reference: streaming_executor_state.py:525 — dispatch
    #: under object-store budgets). None = auto: a quarter of the shm
    #: arena when one exists, else 256 MiB. The streaming executor
    #: shrinks a stage's task window to ~budget/observed-block-size, so
    #: pipelines over huge blocks stop queueing arena-blowing amounts
    #: of output.
    max_in_flight_bytes: Optional[int] = None
    #: Default rows per batch for iter_batches when unspecified.
    default_batch_size: int = 256
    #: Default output partitions for groupby's hash shuffle.
    groupby_num_partitions: int = 8
    #: Verify CRCs when reading TFRecord files.
    tfrecord_verify_crc: bool = True

    _instance: ClassVar[Optional["DataContext"]] = None
    _lock: ClassVar[threading.Lock] = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _set_current(cls, ctx: Optional["DataContext"]) -> None:
        with cls._lock:
            cls._instance = ctx
