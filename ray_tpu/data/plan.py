"""Logical plan + rule-based optimizer.

Capability-equivalent to the reference's plan layer
(reference: python/ray/data/_internal/logical/ — logical operators,
optimizers.py rewrite rules; planner/ logical→physical): datasets are a
chain of logical ops; optimization fuses adjacent row/batch transforms so
one task does the whole fused stage per block (the reference's operator
fusion rule).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOp:
    """Base logical operator; `parent` forms the chain."""

    def __init__(self, parent: Optional["LogicalOp"], name: str):
        self.parent = parent
        self.name = name

    def chain(self) -> List["LogicalOp"]:
        ops: List[LogicalOp] = []
        op: Optional[LogicalOp] = self
        while op is not None:
            ops.append(op)
            op = op.parent
        return list(reversed(ops))

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class Read(LogicalOp):
    def __init__(self, read_tasks: List[Callable[[], Any]], name: str):
        super().__init__(None, name)
        self.read_tasks = read_tasks


class FromBlocks(LogicalOp):
    def __init__(self, blocks: List[Any], name: str = "from_blocks"):
        super().__init__(None, name)
        self.blocks = blocks


@dataclass
class _MapSpec:
    kind: str                     # "batches" | "rows" | "filter" | "flat"
    fn: Any                       # callable or (cls, args, kwargs)
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    fn_constructor_args: Tuple = ()
    fn_constructor_kwargs: Dict[str, Any] = field(default_factory=dict)


class MapLike(LogicalOp):
    def __init__(self, parent: LogicalOp, spec: _MapSpec, *,
                 compute: Optional[Any] = None,
                 num_cpus: float = 1, num_tpus: float = 0,
                 concurrency: Optional[int] = None):
        super().__init__(parent, f"Map[{spec.kind}]")
        self.specs = [spec]
        self.compute = compute
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.concurrency = concurrency

    def can_fuse_with(self, other: "MapLike") -> bool:
        return (self.compute is None and other.compute is None
                and self.num_tpus == other.num_tpus == 0)


class Limit(LogicalOp):
    def __init__(self, parent: LogicalOp, n: int):
        super().__init__(parent, f"Limit[{n}]")
        self.n = n


class Repartition(LogicalOp):
    def __init__(self, parent: LogicalOp, n: int):
        super().__init__(parent, f"Repartition[{n}]")
        self.n = n


class RandomShuffle(LogicalOp):
    def __init__(self, parent: LogicalOp, seed: Optional[int]):
        super().__init__(parent, "RandomShuffle")
        self.seed = seed


class Union(LogicalOp):
    def __init__(self, parent: LogicalOp, others: List[LogicalOp]):
        super().__init__(parent, "Union")
        self.others = others


class Sort(LogicalOp):
    def __init__(self, parent: LogicalOp, key: str, descending: bool):
        super().__init__(parent, f"Sort[{key}]")
        self.key = key
        self.descending = descending


def optimize(root: LogicalOp) -> LogicalOp:
    """Fuse adjacent MapLike ops (reference: operator fusion rule in
    data/_internal/logical/rules/operator_fusion.py)."""
    ops = root.chain()
    out: List[LogicalOp] = []
    for op in ops:
        if (out and isinstance(op, MapLike) and isinstance(out[-1], MapLike)
                and out[-1].can_fuse_with(op)):
            prev = out[-1]
            fused = MapLike(prev.parent, prev.specs[0],
                            compute=prev.compute, num_cpus=prev.num_cpus,
                            num_tpus=prev.num_tpus,
                            concurrency=prev.concurrency)
            fused.specs = prev.specs + op.specs
            fused.name = f"Fused[{'+'.join(s.kind for s in fused.specs)}]"
            out[-1] = fused
        else:
            out.append(op)
    # Re-link the chain.
    prev = None
    for op in out:
        op.parent = prev
        prev = op
    return out[-1]
