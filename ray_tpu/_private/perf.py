"""Core-runtime microbenchmarks.

Capability-equivalent to the reference's microbenchmark suite
(reference: python/ray/_private/ray_perf.py:129-399 — single/multi
client tasks, 1:1 and n:n sync/async actor calls, puts; run by
`ray microbenchmark`, recorded in release_logs microbenchmark.json =
the BASELINE.md rows). Each workload prints `name: N ops/s`.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, List


def _rate(fn: Callable[[], int], min_seconds: float) -> float:
    """Run fn repeatedly for >= min_seconds; returns ops/sec."""
    total = 0
    t0 = time.perf_counter()
    while True:
        total += fn()
        dt = time.perf_counter() - t0
        if dt >= min_seconds:
            return total / dt


def run_microbenchmarks(quick: bool = False) -> Iterator[str]:
    import numpy as np

    import ray_tpu as ray

    ray.shutdown()
    ray.init(num_cpus=4, num_tpus=0)
    dur = 0.5 if quick else 3.0
    batch = 100 if quick else 1000

    @ray.remote
    def tiny():
        return b"ok"

    # warmup
    ray.get([tiny.remote() for _ in range(10)])

    def tasks_batch():
        ray.get([tiny.remote() for _ in range(batch)])
        return batch

    yield (f"tasks_per_second: "
           f"{_rate(tasks_batch, dur):.1f} ops/s")

    @ray.remote
    class Pong:
        def ping(self, x=None):
            return x

    actor = Pong.remote()
    ray.get(actor.ping.remote())

    def sync_actor_calls():
        n = batch // 10
        for _ in range(n):
            ray.get(actor.ping.remote())
        return n

    yield (f"actor_calls_1_1_sync_per_second: "
           f"{_rate(sync_actor_calls, dur):.1f} ops/s")

    def async_actor_calls():
        ray.get([actor.ping.remote() for _ in range(batch)])
        return batch

    yield (f"actor_calls_1_1_async_per_second: "
           f"{_rate(async_actor_calls, dur):.1f} ops/s")

    # Release the 1:1 actor's CPU before the n:n pool — on a 4-CPU
    # runtime a 5th 1-CPU actor would never schedule and the benchmark
    # would wait forever.
    ray.kill(actor)
    actors = [Pong.remote() for _ in range(4)]
    ray.get([a.ping.remote() for a in actors])

    def nn_actor_calls():
        futs = []
        for a in actors:
            futs.extend(a.ping.remote() for _ in range(batch // 4))
        ray.get(futs)
        return batch

    yield (f"actor_calls_n_n_async_per_second: "
           f"{_rate(nn_actor_calls, dur):.1f} ops/s")

    small = b"x" * 1024

    def puts():
        refs = [ray.put(small) for _ in range(batch)]
        del refs
        return batch

    yield f"puts_1kb_per_second: {_rate(puts, dur):.1f} ops/s"

    mb = np.zeros(1_000_000 // 8, dtype=np.float64)  # 1 MB

    def put_gigabytes():
        n = batch // 10
        for _ in range(n):
            r = ray.put(mb)
            del r
        return n

    rate = _rate(put_gigabytes, dur)
    yield f"put_gigabytes_per_second: {rate * 1e6 / 1e9:.3f} GB/s"

    # Compiled-DAG vs dynamic 2-stage call (reference:
    # _private/ray_perf.py:397-399 compiled DAG benchmarks).
    from ray_tpu.dag import InputNode, compile_dag

    @ray.remote
    class Stage:
        def work(self, x):
            return x + 1

    s1, s2 = Stage.remote(), Stage.remote()

    def dyn():
        n = batch // 10
        for i in range(n):
            ray.get(s2.work.remote(s1.work.remote(i)))
        return n

    dyn_rate = _rate(dyn, dur)
    yield (f"dynamic_2stage_per_second: {dyn_rate:.1f} ops/s "
           f"({1e6 / dyn_rate:.0f} us/call)")

    with InputNode() as inp:
        dag = s2.work.bind(s1.work.bind(inp))
    cdag = compile_dag(dag)
    assert cdag.execute(1) == 3

    def comp():
        for i in range(batch):
            cdag.execute(i)
        return batch

    comp_rate = _rate(comp, dur)
    yield (f"compiled_2stage_per_second: {comp_rate:.1f} ops/s "
           f"({1e6 / comp_rate:.0f} us/call, "
           f"{comp_rate / dyn_rate:.1f}x over dynamic)")
    cdag.teardown()

    ray.shutdown()


if __name__ == "__main__":
    for line in run_microbenchmarks():
        print(line)
