"""Per-runtime session directory.

Capability-equivalent of the reference's session layout
(reference: python/ray/_private/node.py — /tmp/ray/session_<ts>_<pid>/
with logs/ underneath, tailed by the log monitor and served by the
dashboard's log viewer): every runtime init gets a fresh directory; it
is left on disk at shutdown for postmortem inspection.
"""

from __future__ import annotations

import datetime
import os
import threading
from typing import Optional

_lock = threading.Lock()
_session_dir: Optional[str] = None

BASE = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")


def new_session() -> str:
    """Create and activate a fresh session directory."""
    global _session_dir
    ts = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S-%f")
    path = os.path.join(BASE, f"session_{ts}_{os.getpid()}")
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    with _lock:
        _session_dir = path
    # "session_latest" convenience symlink (reference keeps the same).
    link = os.path.join(BASE, "session_latest")
    try:
        if os.path.islink(link) or os.path.exists(link):
            os.remove(link)
        os.symlink(path, link)
    except OSError:
        pass
    return path


def session_dir() -> str:
    """Active session dir (creating one if the runtime never did)."""
    with _lock:
        if _session_dir is not None:
            return _session_dir
    return new_session()


def logs_dir() -> str:
    return os.path.join(session_dir(), "logs")


def clear_session() -> None:
    global _session_dir
    with _lock:
        _session_dir = None
