"""Usage stats — local-only, opt-out recording stub.

Capability-equivalent of the reference's usage reporting
(reference: python/ray/_private/usage/usage_lib.py — opt-out telemetry
ping with cluster metadata). This environment has zero egress, so the
capability is reduced to its honest core: collect the same shape of
report and write it to the session directory; nothing ever leaves the
machine. Disable with RAY_TPU_USAGE_STATS_ENABLED=0 (reference env:
RAY_USAGE_STATS_ENABLED).
"""

from __future__ import annotations

import json
import os
import platform
import threading
import time
from typing import Any, Dict

_lock = threading.Lock()
_features: set = set()


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False")


def record_library_usage(feature: str) -> None:
    """Tag a library/feature as used this session (reference:
    usage_lib.record_library_usage)."""
    if not usage_stats_enabled():
        return
    with _lock:
        _features.add(feature)


def build_report() -> Dict[str, Any]:
    import ray_tpu

    with _lock:
        feats = sorted(_features)
    return {
        "schema_version": 1,
        "timestamp": time.time(),
        "version": ray_tpu.__version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "libraries_used": feats,
    }


def write_report() -> str:
    """Persist the report into the session dir (no network egress)."""
    from .session import session_dir

    path = os.path.join(session_dir(), "usage_stats.json")
    with open(path, "w") as f:
        json.dump(build_report(), f, indent=1)
    return path
