"""One host-stats schema for every reporter (reference: the psutil
collection in dashboard/modules/reporter) — the daemon's heartbeat
host section and the dashboard head's own entry must stay
field-compatible, so both build it here."""

from __future__ import annotations

from typing import Any, Dict

# psutil's cpu_percent(interval=None) measures since the PREVIOUS call
# and always reports 0.0 on its first call in a process. Prime it once
# here so the daemon's first heartbeat carries a real number (the
# dashboard head primes separately at server startup for its own
# sampling loop).
_cpu_primed = False


def _accelerator_stats() -> Dict[str, Any]:
    """Accelerator fields riding the same heartbeat schema: device
    count/kind from the env-probing helpers, per-device memory from
    jax when a backend is actually up. Never raises; absent hardware
    contributes nothing."""
    out: Dict[str, Any] = {}
    try:
        from ray_tpu._private import accelerators

        chips = accelerators.num_chips_per_host()
        if chips:
            out["accelerator_count"] = chips
        kind = accelerators.accelerator_type()
        if kind:
            out["accelerator_type"] = kind
    except Exception:  # noqa: BLE001 — probe must not break heartbeats
        return out
    try:
        import sys

        # Only consult an ALREADY-IMPORTED jax: importing it here would
        # drag backend init into every heartbeat path.
        jax = sys.modules.get("jax")
        if jax is not None and out.get("accelerator_count"):
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
            in_use = stats.get("bytes_in_use")
            if limit:
                out["accelerator_mem_total"] = int(limit)
            if in_use is not None:
                out["accelerator_mem_used"] = int(in_use)
    except Exception:  # noqa: BLE001 — cpu-only jax, no memory_stats
        pass
    return out


def collect_host_stats() -> Dict[str, Any]:
    """cpu/mem/disk (+ accelerator) snapshot; {} when psutil is
    unavailable."""
    global _cpu_primed
    try:
        import psutil
    except Exception:  # noqa: BLE001 — optional dep
        return {}
    try:
        if not _cpu_primed:
            # One-time short blocking sample: interval=None's first
            # call in a process always returns 0.0, and a prime-then-
            # read pair measures a ~0s window (equally meaningless).
            cpu = psutil.cpu_percent(interval=0.05)
            _cpu_primed = True
        else:
            cpu = psutil.cpu_percent(interval=None)
        vm = psutil.virtual_memory()
        du = psutil.disk_usage("/")
        stats = {
            "cpu_percent": cpu,
            "cpu_count": psutil.cpu_count(),
            "mem_total": vm.total,
            "mem_percent": vm.percent,
            "disk_percent": du.percent,
        }
        stats.update(_accelerator_stats())
        return stats
    except Exception:  # noqa: BLE001 — platform quirk
        return {}
