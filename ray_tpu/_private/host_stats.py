"""One host-stats schema for every reporter (reference: the psutil
collection in dashboard/modules/reporter) — the daemon's heartbeat
host section and the dashboard head's own entry must stay
field-compatible, so both build it here."""

from __future__ import annotations

from typing import Any, Dict


def collect_host_stats() -> Dict[str, Any]:
    """cpu/mem/disk snapshot; {} when psutil is unavailable."""
    try:
        import psutil
    except Exception:  # noqa: BLE001 — optional dep
        return {}
    try:
        vm = psutil.virtual_memory()
        du = psutil.disk_usage("/")
        return {
            "cpu_percent": psutil.cpu_percent(interval=None),
            "cpu_count": psutil.cpu_count(),
            "mem_total": vm.total,
            "mem_percent": vm.percent,
            "disk_percent": du.percent,
        }
    except Exception:  # noqa: BLE001 — platform quirk
        return {}
