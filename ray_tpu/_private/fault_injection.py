"""Fault injection — chaos harness for resilience testing.

Capability-equivalent of the reference's chaos tooling
(reference: python/ray/_private/test_utils.py — NodeKillerActor :1464,
ResourceKillerActor :1396, kill_raylet :1874; release/nightly_tests
setup_chaos.py; `ray kill-random-node` CLI scripts.py:1378): actors
that periodically kill cluster components while a workload runs, so
retries / lineage reconstruction / actor restarts are exercised under
real failure interleavings rather than single hand-placed faults.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional


class NodeKiller:
    """Periodically removes random non-head nodes from the scheduler
    (reference: NodeKillerActor). Run as a plain object in the driver —
    killing the node that hosts you is the reference actor's classic
    self-inflicted failure mode."""

    def __init__(self, *, interval_s: float = 1.0,
                 max_kills: int = 3, seed: Optional[int] = None):
        self.interval_s = interval_s
        self.max_kills = max_kills
        self._rng = random.Random(seed)
        self.killed: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="node-killer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 2)
            self._thread = None

    def _run(self) -> None:
        from ..core.runtime import global_runtime_or_none

        while not self._stop.wait(self.interval_s):
            if len(self.killed) >= self.max_kills:
                return
            rt = global_runtime_or_none()
            if rt is None:
                continue  # runtime not up yet — keep polling
            victims = [n for n in rt.scheduler.nodes()
                       if n.node_id != rt.head_node_id and n.alive]
            if not victims:
                continue
            node = self._rng.choice(victims)
            self.kill_node(node.node_id)

    def kill_node(self, node_id: str) -> None:
        from ..core.runtime import global_runtime_or_none

        rt = global_runtime_or_none()
        if rt is None:
            return
        rt.scheduler.remove_node(node_id)
        self.killed.append(node_id)


def kill_random_node(exclude_head: bool = True) -> Optional[str]:
    """One-shot random node kill (reference: `ray kill-random-node`,
    scripts.py:1378). Returns the killed node id or None."""
    from ..core.runtime import global_runtime_or_none

    rt = global_runtime_or_none()
    if rt is None:
        return None
    victims = [n for n in rt.scheduler.nodes()
               if n.alive and not (exclude_head
                                   and n.node_id == rt.head_node_id)]
    if not victims:
        return None
    node = random.choice(victims)
    rt.scheduler.remove_node(node.node_id)
    return node.node_id


class ServeFaultInjector:
    """Serve-plane fault points, armed per replica through the
    Replica.inject_fault actor method (serve/replica.py): `stall`
    delays every request (overload scripting), `crash_on_request` makes
    the replica die as if its process was killed on the next N requests
    (exercises handle-side retry + controller replacement), and
    `slow_health_probe` delays health_check() past the controller's
    timeout (exercises health-check-driven restart). Deterministic —
    chaos tests script exact failure interleavings instead of racing a
    background killer."""

    def __init__(self, controller):
        self._controller = controller
        self.armed: List[tuple] = []

    def _replicas(self, deployment: str):
        from .. import get as ray_get

        replicas, _ = ray_get(
            self._controller.get_replicas.remote(deployment))
        return replicas

    def _arm(self, deployment: str, kind: str, value,
             replica_index: Optional[int]) -> int:
        from .. import get as ray_get

        replicas = self._replicas(deployment)
        targets = (replicas if replica_index is None
                   else [replicas[replica_index]])
        for r in targets:
            ray_get(r.inject_fault.remote(kind, value), timeout=10)
        self.armed.append((deployment, kind, value, replica_index))
        return len(targets)

    def stall(self, deployment: str, stall_s: float,
              replica_index: Optional[int] = None) -> int:
        """Every request to the target replica(s) sleeps `stall_s`
        before running. → replicas armed."""
        return self._arm(deployment, "stall_s", float(stall_s),
                         replica_index)

    def crash_on_request(self, deployment: str, count: int = 1,
                         replica_index: Optional[int] = 0) -> int:
        """The target replica dies (actor-death semantics, not a user
        exception) on its next `count` requests. → replicas armed."""
        return self._arm(deployment, "crash_on_request", int(count),
                         replica_index)

    def slow_health_probe(self, deployment: str, delay_s: float,
                          replica_index: Optional[int] = 0) -> int:
        """health_check() on the target replica(s) sleeps `delay_s` —
        set it past health_check_timeout_s to trigger the controller's
        unhealthy → restart path. → replicas armed."""
        return self._arm(deployment, "health_probe_delay_s",
                         float(delay_s), replica_index)

    def clear(self, deployment: str) -> None:
        """Disarm every fault on every live replica (replicas replaced
        since arming never had faults)."""
        from .. import get as ray_get

        for r in self._replicas(deployment):
            for kind in ("stall_s", "crash_on_request",
                         "health_probe_delay_s"):
                try:
                    ray_get(r.inject_fault.remote(kind, None),
                            timeout=10)
                except Exception:  # noqa: BLE001 — dead replica
                    pass
        self.armed = [a for a in self.armed if a[0] != deployment]


class WorkerKiller:
    """Kills random spawned worker PROCESSES mid-task (reference:
    ResourceKillerActor targeting workers): exercises worker-crash
    recovery — the pool respawns, tasks retry per max_retries, actors
    restart per max_restarts."""

    def __init__(self, *, interval_s: float = 0.5,
                 max_kills: int = 2, seed: Optional[int] = None):
        self.interval_s = interval_s
        self.max_kills = max_kills
        self._rng = random.Random(seed)
        self.killed: List[int] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="worker-killer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 2)
            self._thread = None

    def _run(self) -> None:
        from ..core.runtime import global_runtime_or_none

        while not self._stop.wait(self.interval_s):
            if len(self.killed) >= self.max_kills:
                return
            rt = global_runtime_or_none()
            if rt is None or rt.worker_pool is None:
                continue  # runtime/pool not up yet — keep polling
            with rt.worker_pool._lock:
                workers = [w for w in rt.worker_pool._all.values()
                           if w.proc.poll() is None]
            if not workers:
                continue
            w = self._rng.choice(workers)
            try:
                w.proc.kill()
                self.killed.append(w.worker_id)
            except Exception:  # noqa: BLE001
                pass
