"""Log monitor — tails session worker logs back to the driver.

Capability-equivalent of the reference's LogMonitor
(reference: python/ray/_private/log_monitor.py:103 — tails
/tmp/ray/session_*/logs/*, publishes lines to the driver, which prints
them with a worker prefix; `log_to_driver`): here a daemon thread polls
the session's logs/ directory and forwards appended lines to a sink
(default: driver stdout with an `(worker N)` prefix).
"""

from __future__ import annotations

import glob
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

DEFAULT_POLL_S = 0.2


class LogMonitor:
    def __init__(self, logs_dir: str,
                 sink: Optional[Callable[[str, str], None]] = None,
                 poll_interval: float = DEFAULT_POLL_S):
        self.logs_dir = logs_dir
        self.sink = sink or self._print_sink
        self.poll = poll_interval
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "LogMonitor":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ray-tpu-log-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll + 1)
        self.poll_once()  # final drain so shutdown doesn't drop lines

    # -- tailing --------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - never kill the tailer
                pass

    def poll_once(self) -> int:
        """Forward any newly appended lines. → number of lines."""
        n = 0
        for path in sorted(glob.glob(os.path.join(self.logs_dir, "*"))):
            if not os.path.isfile(path):
                continue
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                if size < offset:  # truncated/rotated: restart
                    self._offsets[path] = 0
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(size - offset)
            except OSError:
                continue
            # Only complete lines; keep the partial tail for next poll
            # (binary offsets — text decoding must not skew them). \r
            # counts as a terminator too, or progress-bar output would
            # be re-read forever and never forwarded.
            done = max(data.rfind(b"\n"), data.rfind(b"\r"))
            if done < 0:
                continue
            self._offsets[path] = offset + done + 1
            source = os.path.basename(path)
            text = data[:done].decode("utf-8", "replace")
            # CRLF collapses first so \r handling can't fabricate blank
            # lines; REAL blank lines are forwarded (print() separators).
            text = text.replace("\r\n", "\n").replace("\r", "\n")
            lines = text.split("\n")
            if lines and lines[-1] == "" and text.endswith("\n"):
                # data[:done] ending in CRLF leaves one artifact "".
                lines.pop()
            for line in lines:
                self.sink(source, line)
                n += 1
        return n

    @staticmethod
    def _print_sink(source: str, line: str) -> None:
        # "(worker-3.out) hello" — mirrors the reference's
        # "(pid=...) hello" driver echo.
        print(f"({source}) {line}", file=sys.stderr, flush=True)
