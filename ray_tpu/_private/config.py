"""Config/flag registry.

Equivalent in capability to the reference's RayConfig X-macro registry
(reference: src/ray/common/ray_config_def.h — 220 RAY_CONFIG(type,name,default)
flags, overridable via RAY_<name> env vars or a JSON system-config blob pushed
from the head node). Here: declarative flag table, `RAY_TPU_<NAME>` env
override, plus programmatic override via ``Config.apply(dict)`` which is what
``init(_system_config=...)`` feeds.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict


_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, typ: Callable, default: Any, doc: str):
        self.name = name
        self.type = typ
        self.default = default
        self.doc = doc


class Config:
    """Process-wide flag registry (singleton at module bottom)."""

    _FLAGS: Dict[str, _Flag] = {}

    @classmethod
    def _define(cls, name: str, typ: Callable, default: Any, doc: str = ""):
        cls._FLAGS[name] = _Flag(name, typ, default, doc)

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[str, Any] = {}
        self.reload()

    def reload(self):
        with self._lock:
            self._values.clear()
            for name, flag in self._FLAGS.items():
                env = os.environ.get(_ENV_PREFIX + name.upper())
                if env is not None:
                    if flag.type is bool:
                        self._values[name] = _parse_bool(env)
                    elif flag.type in (dict, list):
                        self._values[name] = json.loads(env)
                    else:
                        self._values[name] = flag.type(env)
                else:
                    self._values[name] = flag.default

    def apply(self, overrides: Dict[str, Any] | None):
        if not overrides:
            return
        with self._lock:
            for k, v in overrides.items():
                if k not in self._FLAGS:
                    raise ValueError(f"Unknown system config flag: {k!r}")
                self._values[k] = self._FLAGS[k].type(v)

    def __getattr__(self, name: str):
        # only called when normal attribute lookup fails
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def dump(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._values)


_D = Config._define

# --- core runtime ---
_D("task_retry_delay_ms", int, 0, "Delay between task retry attempts.")
_D("default_max_retries", int, 3, "Default max retries for tasks.")
_D("default_actor_max_restarts", int, 0, "Default max restarts for actors.")
_D("inline_object_max_bytes", int, 100 * 1024,
   "Objects smaller than this are stored inline in the in-process memory "
   "store rather than the shared-memory store (reference inlines <100KB).")
_D("memory_store_max_bytes", int, 2 * 1024**3,
   "Soft cap for the in-process memory store.")
_D("object_store_memory_bytes", int, 1 * 1024**3,
   "Capacity of the per-node shared-memory object store.")
_D("object_spilling_dir", str, "",
   "Directory for spilled objects ('' = <session_dir>/spill).")
_D("memory_store_spill_threshold_bytes", int, 2 * 1024**3,
   "Spill memory-store objects to disk past this many in-memory bytes "
   "(0 = never spill).")
_D("object_store_full_initial_retry_ms", int, 10, "")
_D("object_store_full_max_retries", int, 10, "")
_D("worker_pool_size", int, 0,
   "Number of task-executor threads per worker (0 = num_cpus resource).")
_D("actor_queue_max", int, 10000, "Per-actor pending-call queue bound.")
_D("memory_monitor_threshold", float, 0.95,
   "Node memory-usage fraction above which the memory monitor kills "
   "the last-submitted retriable task's worker to relieve pressure "
   "(reference: memory_monitor.h + worker_killing_policy.h). "
   "0 disables the monitor.")
_D("memory_monitor_interval_ms", int, 250,
   "Memory monitor sampling interval.")
_D("memory_monitor_usage_file", str, "",
   "Chaos/fault-injection hook: read the usage fraction from this "
   "file instead of /proc/meminfo.")
_D("generator_backpressure_max_items", int, 16,
   "Streaming generators pause the producer once this many yielded "
   "items await consumption (reference: GeneratorWaiter backpressure, "
   "core_worker.h). 0 disables backpressure.")
_D("get_timeout_warning_s", float, 30.0,
   "Warn if a blocking get waits longer than this.")
_D("health_check_period_ms", int, 1000, "Node health-check interval.")
_D("health_check_failure_threshold", int, 5, "")
_D("scheduler_spread_threshold", float, 0.5,
   "Hybrid policy: prefer local node until it is this utilized.")
_D("scheduler_top_k_fraction", float, 0.2,
   "Hybrid policy: best node among a random top-k fraction.")
_D("lineage_max_bytes", int, 256 * 1024**2, "Lineage table soft cap.")
_D("enable_timeline", bool, True, "Record task timeline events.")
_D("log_to_driver", bool, True,
   "Tail spawned-worker logs back to the driver's stderr.")
_D("task_event_buffer_max", int, 100_000, "Max buffered task state events.")
_D("flight_recorder_enabled", bool, True,
   "Always-on bounded ring of structured runtime events (scheduler, "
   "object transfer, serve, autoscaler) dumped on unhandled failures "
   "and via `ray_tpu debug dump`.")
_D("flight_recorder_max_events", int, 4096,
   "Ring-buffer capacity of the flight recorder; oldest events drop.")
_D("flight_recorder_dir", str, "",
   "Directory for automatic flight-recorder dumps "
   "('' = <session_dir>/flight_recorder, or the system tempdir when "
   "no runtime is alive).")
_D("flight_recorder_auto_dump_min_interval_s", float, 5.0,
   "Rate limit between automatic crash dumps (a crash storm must not "
   "turn the recorder into a disk-filling loop).")
_D("gang_schedule_timeout_s", float, 60.0,
   "Timeout for atomically acquiring all bundles of a placement group.")
_D("cluster_poll_interval_s", float, 0.5,
   "Driver-side poll interval for cluster membership + load reports "
   "(resource-view sync; capability of reference ray_syncer.h).")
_D("actor_replace_timeout_s", float, 10.0,
   "How long a restarting actor waits for a surviving node with "
   "capacity before giving up (multi-host actor recovery).")
# --- continuous observability plane ---
_D("contprof_enabled", bool, True,
   "Always-on low-duty-cycle profiler in every long-lived process "
   "(driver, node daemons, workers): periodic short StackSampler "
   "captures retained on disk for postmortem flamegraphs.")
_D("contprof_interval_s", float, 60.0,
   "Seconds between continuous-profiler captures.")
_D("contprof_duration_s", float, 2.0,
   "Length of each continuous-profiler capture (duty cycle = "
   "duration / interval; defaults give ~3%).")
_D("contprof_sample_interval_s", float, 0.01,
   "Stack-sampling period within a capture.")
_D("contprof_retention_count", int, 240,
   "Max retained profile snapshots per process role dir; oldest "
   "evicted first.")
_D("contprof_retention_bytes", int, 32 * 1024**2,
   "Max total bytes of retained profile snapshots; oldest evicted "
   "first.")
_D("contprof_dir", str, "",
   "Directory for retained profile snapshots ('' = "
   "<session_dir>/contprof). Daemons propagate their resolved dir to "
   "workers so one node shares one ring.")
_D("metrics_history_enabled", bool, True,
   "Embedded metrics TSDB: a scraper thread samples the metrics "
   "registry into fixed-size per-series ring buffers, queryable via "
   "/api/metrics/history and `ray_tpu obs`.")
_D("metrics_history_resolution_s", float, 10.0,
   "Seconds between metrics-history scrapes.")
_D("metrics_history_window_s", float, 3600.0,
   "Per-series history window; ring capacity = window / resolution.")
_D("anomaly_detection_enabled", bool, True,
   "Per-plane straggler/outlier watchdogs (RLHF rollout tok/s, serve "
   "replica TTFT, dispatch-handler p95 spikes) feeding "
   "ray_tpu_anomaly_total and flight-recorder `anomaly` events.")
_D("anomaly_mad_k", float, 3.0,
   "Robust outlier threshold: flag values more than k median absolute "
   "deviations below/above the fleet median.")
_D("anomaly_ewma_alpha", float, 0.3,
   "Smoothing factor for per-subject EWMAs fed to the detectors.")
_D("anomaly_min_samples", int, 4,
   "Detectors stay silent until a cohort has at least this many "
   "subjects/samples (MAD of 2 points is noise).")
_D("anomaly_p95_spike_factor", float, 3.0,
   "Dispatch-loop watchdog: flag a handler whose current p95 exceeds "
   "this multiple of its trailing-window median p95.")
# --- outstanding-resource ledger ---
_D("ledger_enabled", bool, True,
   "Cluster-wide outstanding-resource ledger: periodic snapshots of "
   "every plane's held-resource set (serve admission slots, dispatch "
   "ledger charges, worker checkouts, shm pins, inflight pulls, live "
   "task/actor rows) with owner, age, and acquisition site, plus "
   "cross-plane reconciliation and age-based leak detection.")
_D("ledger_interval_s", float, 5.0,
   "Seconds between ledger snapshot + reconciliation passes.")
_D("ledger_leak_k", float, 8.0,
   "Age-based leak detection: flag an entry whose age exceeds its "
   "plane's observed p99 hold time multiplied by this factor.")
_D("ledger_leak_min_age_s", float, 30.0,
   "Floor below which an entry is never a leak suspect, regardless of "
   "the plane's p99 (young planes have noisy percentiles).")
_D("ledger_capture_sites", bool, True,
   "Stamp each acquisition with its call site (file:line:function) so "
   "leak findings carry the acquisition backtrace. Cheap (one "
   "sys._getframe walk per acquisition); disable for micro-benches.")
_D("ledger_invariant_patience", int, 2,
   "Consecutive failing snapshots before a reconciliation invariant "
   "turns red (tolerates heartbeat skew and in-flight churn).")
_D("ledger_max_entries_per_plane", int, 512,
   "Bound on ledger entries shipped per plane per snapshot (oldest "
   "kept — they are the leak candidates).")
# --- TPU / device ---
_D("tpu_devices_per_host", int, 0, "0 = autodetect via jax.local_devices().")
_D("prefetch_to_device_buffers", int, 2,
   "Double-buffer depth for host→HBM input pipelines.")
_D("mesh_allow_cpu_fallback", bool, True,
   "Build meshes from CPU devices when no TPU is present (tests).")

config = Config()
