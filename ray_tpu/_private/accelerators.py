"""TPU accelerator discovery.

Capability-equivalent of the reference's TPU support
(reference: python/ray/_private/accelerators/tpu.py — resource "TPU",
TPU_VISIBLE_CHIPS visibility control :13-46, accelerator-type and pod
name discovery from GKE/GCE metadata env, per-pod custom resources like
"TPU-v4-16-head"): reads the libtpu/GKE environment so the scheduler
can size the "TPU" resource and gang-schedule onto slices without
probing jax (which would grab the chips).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

# Env contract (set by GKE TPU webhooks / xla runtime):
VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"     # e.g. "v5p-64"
WORKER_ID_ENV = "TPU_WORKER_ID"                   # host index in the pod
WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"     # csv of pod hosts
TPU_NAME_ENV = "TPU_NAME"                         # pod/slice name
CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"  # e.g. "2,2,1"


def get_visible_chips() -> Optional[List[str]]:
    """Chip ids this process may use; None = unrestricted. An EMPTY env
    value means ZERO chips (the CUDA_VISIBLE_DEVICES contract — '' is a
    restriction, not an absence of one; reference: tpu.py
    get_current_process_visible_accelerator_ids)."""
    v = os.environ.get(VISIBLE_CHIPS_ENV)
    if v is None:
        return None
    return [c.strip() for c in v.split(",") if c.strip() != ""]


def set_visible_chips(chip_ids: List[str]) -> None:
    """Restrict this process to the given chips (reference:
    tpu.py set_current_process_visible_accelerator_ids)."""
    os.environ[VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)


def num_chips_per_host() -> int:
    """Chips THIS PROCESS may use: the visibility list wins (the
    CUDA_VISIBLE_DEVICES analog — a restricted process must not
    advertise the whole host), then the host bounds env (e.g.
    "2,2,1" → 4), then probing jax; 0 if undiscoverable."""
    visible = get_visible_chips()
    if visible is not None:
        return len(visible)
    bounds = os.environ.get(CHIPS_PER_HOST_BOUNDS_ENV)
    if bounds:
        n = 1
        try:
            for d in bounds.split(","):
                n *= int(d)
            return n
        except ValueError:
            pass
    try:
        import jax

        return len([d for d in jax.local_devices()
                    if d.platform != "cpu"])
    except Exception:  # noqa: BLE001
        return 0


def accelerator_type() -> Optional[str]:
    """"v5p-64"-style type string (reference: tpu.py
    get_current_node_accelerator_type via GCE metadata; here env-only —
    zero egress)."""
    return os.environ.get(ACCELERATOR_TYPE_ENV) or None


def pod_name() -> Optional[str]:
    return os.environ.get(TPU_NAME_ENV) or None


def worker_id() -> int:
    try:
        return int(os.environ.get(WORKER_ID_ENV, "0"))
    except ValueError:
        return 0


def pod_worker_count() -> int:
    hosts = os.environ.get(WORKER_HOSTNAMES_ENV, "")
    return len([h for h in hosts.split(",") if h.strip()]) or 1


def pod_resources() -> Dict[str, float]:
    """Custom resources advertising pod membership (reference:
    tpu.py — "TPU-<type>-head" on worker 0 plus a per-pod name
    resource, used for gang placement of one job per slice)."""
    out: Dict[str, float] = {}
    acc = accelerator_type()
    name = pod_name()
    if acc:
        out[f"TPU-{acc}"] = 1.0
        if worker_id() == 0:
            out[f"TPU-{acc}-head"] = 1.0
    if name:
        out[f"TPU-pod-{name}"] = 1.0
    return out
