from .node import (
    ActorMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ActorMethodNode",
    "InputNode", "MultiOutputNode",
]
