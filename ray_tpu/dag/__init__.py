from .compiled import Channel, CompiledDAG, compile_dag
from .node import (
    ActorMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ActorMethodNode",
    "InputNode", "MultiOutputNode",
    "CompiledDAG", "compile_dag", "Channel",
]
