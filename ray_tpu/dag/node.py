"""Lazy DAG construction via .bind().

Capability-equivalent to the reference's DAG layer
(reference: python/ray/dag/dag_node.py — DAGNode/InputNode/OutputNode and
`python/ray/dag/compiled_dag_node.py` for the compiled execution): builds a
static graph of function/actor-method calls that can be executed repeatedly
with `dag.execute(input)`, the substrate for serve app graphs and compiled
channel execution.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Tuple


class DAGNode:
    def __init__(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal --------------------------------------------------------
    def _deps(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def experimental_compile(self, **kwargs):
        """Compile this DAG into pinned actor loops over shared-memory
        channels (reference: dag.experimental_compile / compiled DAGs).
        Returns a CompiledDAG with .execute(value)/.teardown()."""
        from .compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)

    def execute(self, *input_args, **input_kwargs):
        """Execute the DAG rooted at this node; returns ObjectRef(s)."""
        cache: Dict[int, Any] = {}
        return self._execute_node(cache, input_args, input_kwargs)

    def _resolve_args(self, cache, input_args, input_kwargs):
        def r(v):
            if isinstance(v, DAGNode):
                return v._execute_node(cache, input_args, input_kwargs)
            return v

        args = tuple(r(a) for a in self._bound_args)
        kwargs = {k: r(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_node(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(cache, input_args, input_kwargs)
        return cache[key]

    def _execute_impl(self, cache, input_args, input_kwargs):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the DAG's runtime input. Context-manager style:
    ``with InputNode() as inp: ...`` (parity with the reference)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, cache, input_args, input_kwargs):
        if len(input_args) == 1 and not input_kwargs:
            return input_args[0]
        return input_args


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, cache, input_args, input_kwargs):
        args, kwargs = self._resolve_args(cache, input_args, input_kwargs)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """An actor-to-be in the DAG; instantiated once per DAG (lazily)."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None
        self._lock = threading.Lock()

    def _get_or_create(self, cache, input_args, input_kwargs):
        with self._lock:
            if self._handle is None:
                args, kwargs = self._resolve_args(
                    cache, input_args, input_kwargs)
                self._handle = self._actor_cls.remote(*args, **kwargs)
            return self._handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethodBinder(self, name)

    def _execute_impl(self, cache, input_args, input_kwargs):
        return self._get_or_create(cache, input_args, input_kwargs)


class _UnboundMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ActorMethodNode":
        return ActorMethodNode(
            self._class_node, self._method_name, args, kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, target, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._target = target  # ActorHandle or ClassNode
        self._method_name = method_name

    def _resolve_handle(self):
        """The bound actor handle (creating the ClassNode actor if this
        DAG never ran dynamically) — used by experimental_compile."""
        if isinstance(self._target, ClassNode):
            return self._target._get_or_create({}, (), {})
        return self._target

    def _execute_impl(self, cache, input_args, input_kwargs):
        args, kwargs = self._resolve_args(cache, input_args, input_kwargs)
        target = self._target
        if isinstance(target, ClassNode):
            target = target._get_or_create(cache, input_args, input_kwargs)
        method = getattr(target, self._method_name)
        return method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_impl(self, cache, input_args, input_kwargs):
        return [o._execute_node(cache, input_args, input_kwargs)
                for o in self._bound_args]
