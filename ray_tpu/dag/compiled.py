"""Compiled DAG execution over shared-memory channels.

Capability-equivalent of the reference's accelerated/compiled DAGs
(reference: python/ray/dag/compiled_dag_node.py — do_exec_compiled_task
:34 pinned actor loops; python/ray/experimental/channel.py — Channel :48
over mutable plasma objects :37): `compile_dag(dag)` allocates one
channel per edge ONCE, then each participating actor parks in a
read→exec→write loop pinned to the actor (no per-call task submission,
scheduling, or result-store traffic) — the ~10x lower per-call latency
path the reference benchmarks as "compiled DAGs"
(_private/ray_perf.py:397-399).

Channels are the native store's mutable objects (src/shm_store.cc
rts_ch_* — the seqlock buffer equivalent of plasma's experimental
mutable objects) when the native plane is up; an in-process blocking
queue fallback keeps the same semantics otherwise. Channels cross the
driver→actor boundary as SPECS (tag + id) and are re-attached on the
executing side, so nothing unpicklable rides the task path.
"""

from __future__ import annotations

import pickle
import queue as _pyqueue
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

_SENTINEL = b"__ray_tpu_dag_teardown__"

# In-process channel registry (fallback path; shared by driver and
# in-process actors because they share the module).
_PROC_CHANNELS: Dict[str, "_ProcQueue"] = {}
_PROC_LOCK = threading.Lock()


class _ProcQueue:
    def __init__(self):
        self.q: "_pyqueue.Queue[bytes]" = _pyqueue.Queue(maxsize=8)


ChannelSpec = Tuple[str, Any]  # ("proc", key) | ("shm", id_bytes)


def _resolve_shm():
    """This process's shm attachment: the driver's runtime store, or —
    inside a spawned worker process — worker_main's attachment."""
    from ..core.runtime import global_runtime_or_none

    rt = global_runtime_or_none()
    if rt is not None and rt.shm is not None:
        return rt.shm
    from ..core import worker_main

    return worker_main.WORKER_SHM


def _make_spec(use_shm: bool) -> ChannelSpec:
    if use_shm:
        # EXACTLY 28 bytes (ID_LEN): uuid4().bytes is 16 bytes — a
        # short id makes the C side hash garbage past the buffer,
        # which differs per process and breaks cross-process lookup.
        cid = b"dagch" + uuid.uuid4().bytes + uuid.uuid4().bytes[:7]
        assert len(cid) == 28
        return ("shm", cid)
    key = uuid.uuid4().hex
    with _PROC_LOCK:
        _PROC_CHANNELS[key] = _ProcQueue()
    return ("proc", key)


class Channel:
    """One endpoint of a channel; construct per side from its spec
    (reader version state is endpoint-local, seqlock style)."""

    def __init__(self, spec: ChannelSpec, *, create: bool = False,
                 max_size: int = 1 << 20):
        self.spec = spec
        self._version = -1
        kind, key = spec
        if kind == "shm":
            self._store = _resolve_shm()
            if self._store is None:
                raise RuntimeError("shm plane not available")
            if create:
                self._store.channel_create(key, max_size)
        else:
            with _PROC_LOCK:
                self._q = _PROC_CHANNELS[key]

    def write(self, data: bytes) -> None:
        kind, key = self.spec
        if kind == "shm":
            self._store.channel_write(key, data)
        else:
            self._q.q.put(data)

    def read(self, timeout: float = 30.0) -> bytes:
        kind, key = self.spec
        if kind == "shm":
            data, v = self._store.channel_read(
                key, min_version=self._version, timeout=timeout)
            self._version = v
            return data
        return self._q.q.get(timeout=timeout)

    def close(self) -> None:
        kind, key = self.spec
        if kind == "shm":
            try:
                self._store.delete(key)
            except Exception:  # noqa: BLE001
                pass
        else:
            with _PROC_LOCK:
                _PROC_CHANNELS.pop(key, None)


def _compiled_actor_loop(instance, method_name: str,
                         arg_plan: List[tuple],
                         const_kwargs: Dict[str, Any],
                         in_specs: List[ChannelSpec],
                         out_spec: ChannelSpec, timeout: float):
    """Runs ON the actor: read args → run method → write result
    (reference: do_exec_compiled_task's pinned loop).

    arg_plan mirrors the node's bound args positionally: ("ch", i)
    pulls channel i's frame, ("const", v) is a literal.
    """
    method = getattr(instance, method_name)
    ins = [Channel(s) for s in in_specs]
    out = Channel(out_spec)
    while True:
        try:
            frames = [ch.read(timeout=timeout) for ch in ins]
        except (TimeoutError, _pyqueue.Empty):
            continue  # idle is not teardown — keep the DAG alive
        except Exception:  # noqa: BLE001 - channel gone: teardown
            return
        if any(f == _SENTINEL for f in frames):
            out.write(_SENTINEL)  # propagate teardown downstream
            return
        try:
            values = [pickle.loads(f) for f in frames]
            # An upstream stage's error flows through untouched — the
            # failing stage's exception must reach the driver, not be
            # fed into this method as a bogus argument.
            upstream_err = next(
                (v for v in values if isinstance(v, _WrappedError)), None)
            if upstream_err is not None:
                out.write(pickle.dumps(upstream_err))
                continue
            args = [values[i] if kind == "ch" else i
                    for kind, i in arg_plan]
            result = method(*args, **const_kwargs)
            out.write(pickle.dumps(result))
        except Exception as e:  # noqa: BLE001
            try:
                out.write(pickle.dumps(_WrappedError(e)))
            except Exception:  # noqa: BLE001
                out.write(pickle.dumps(_WrappedError(
                    RuntimeError(f"{type(e).__name__}: {e}"))))


class _WrappedError:
    def __init__(self, e: BaseException):
        self.error = e


class CompiledDAG:
    """A compiled pipeline of actor-method nodes.

    Supported shape (matches the reference's early compiled DAGs):
    InputNode → chain of ActorMethodNodes → output, each stage's output
    consumed by exactly one downstream stage.
    """

    def __init__(self, output_node, *, channel_size: int = 1 << 20,
                 timeout: float = 60.0):
        from .node import ActorMethodNode, InputNode

        self._timeout = timeout
        self._closed = False

        order: List[Any] = []
        seen = set()

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for dep in node._deps():
                visit(dep)
            order.append(node)

        visit(output_node)
        self._input = next(
            (n for n in order if isinstance(n, InputNode)), None)
        if self._input is None:
            raise ValueError("compiled DAG needs an InputNode")
        self._nodes = [n for n in order if isinstance(n, ActorMethodNode)]
        if not self._nodes or self._nodes[-1] is not order[-1]:
            raise ValueError(
                "compiled DAG output must be an actor-method node")
        # Single-consumer validation: each producer feeds one stage.
        # Constant bound args/kwargs are captured into the loop;
        # DAG-node kwargs are not supported.
        consumers: Dict[int, int] = {}
        for n in self._nodes:
            for d in n._deps():
                consumers[id(d)] = consumers.get(id(d), 0) + 1
                if not isinstance(d, (ActorMethodNode, InputNode)):
                    raise ValueError(
                        f"unsupported compiled-DAG dep "
                        f"{type(d).__name__}")
            from .node import DAGNode as _DAGNode

            if any(isinstance(v, _DAGNode)
                   for v in n._bound_kwargs.values()):
                raise ValueError(
                    "compiled DAGs do not support DAG-node kwargs; "
                    "pass upstream nodes positionally")
        if any(c > 1 for c in consumers.values()):
            raise ValueError(
                "compiled DAGs support single-consumer channels; an "
                "output is consumed by multiple stages")

        from ..core.runtime import global_runtime_or_none

        rt = global_runtime_or_none()
        use_shm = rt is not None and rt.shm is not None

        # One channel per edge, allocated once (reference: channels
        # allocated at compile time, reused every execute()).
        self._spec_of: Dict[int, ChannelSpec] = {}
        self._chan_of: Dict[int, Channel] = {}
        for node in [self._input] + self._nodes:
            spec = _make_spec(use_shm)
            self._spec_of[id(node)] = spec
            self._chan_of[id(node)] = Channel(spec, create=True,
                                              max_size=channel_size)
        self._in_chan = self._chan_of[id(self._input)]
        self._out_chan = self._chan_of[id(self._nodes[-1])]

        # Park the loop on every actor (injected-callable task).
        import ray_tpu
        from ..core.actor import ActorMethod
        from .node import DAGNode as _DAGNode

        self._loop_refs = []
        for n in self._nodes:
            handle = n._resolve_handle()
            self._check_placement(rt, handle, use_shm)
            in_specs = []
            arg_plan = []
            for a in n._bound_args:
                if isinstance(a, _DAGNode):
                    arg_plan.append(("ch", len(in_specs)))
                    in_specs.append(self._spec_of[id(a)])
                else:
                    arg_plan.append(("const", a))
            ref = ActorMethod(handle, "__ray_tpu_apply__").remote(
                _compiled_actor_loop, n._method_name, arg_plan,
                dict(n._bound_kwargs), in_specs,
                self._spec_of[id(n)], self._timeout)
            self._loop_refs.append(ref)
        # Surface immediate loop-spawn failures (bad method name etc.)
        # instead of a later opaque execute() timeout. Healthy loops
        # never complete, so keep the probe short; execute() re-checks
        # the refs whenever a read times out.
        self._probe_loops(timeout=0.05)

    def _probe_loops(self, timeout: float) -> None:
        import ray_tpu

        ready, _ = ray_tpu.wait(self._loop_refs,
                                num_returns=1, timeout=timeout)
        if ready:
            ray_tpu.get(ready[0])  # raises the loop's error

    @staticmethod
    def _check_placement(rt, handle, use_shm: bool) -> None:
        """Proc-pool actors join compiled DAGs through shared-memory
        channels; without the shm plane the in-process queue fallback
        cannot cross process boundaries."""
        if rt is None or use_shm:
            return
        st = rt._actors.get(handle._actor_id)
        if st is not None and type(st).__name__.startswith("Proc"):
            raise RuntimeError(
                "compiled DAGs over process-pool actors need the native "
                "shm store (build src/ and enable the shm plane)")

    # -- execution ------------------------------------------------------
    def execute(self, value: Any) -> Any:
        if self._closed:
            raise RuntimeError("compiled DAG torn down")
        self._in_chan.write(pickle.dumps(value))
        try:
            frame = self._out_chan.read(timeout=self._timeout)
        except (TimeoutError, _pyqueue.Empty):
            # The in-flight result may still land later; consuming it on
            # the NEXT execute would desync input/output pairing — the
            # DAG is no longer trustworthy. Surface a loop error if one
            # exists, else a normalized timeout; either way, brick it.
            self._closed = True
            self._probe_loops(timeout=0)
            raise TimeoutError(
                f"compiled DAG execute() timed out after "
                f"{self._timeout}s; DAG torn down (results could no "
                f"longer be paired with inputs)")
        out = pickle.loads(frame)
        if isinstance(out, _WrappedError):
            raise out.error
        return out

    def teardown(self) -> None:
        if self._closed:
            return
        self._closed = True
        # The sentinel flows through every stage, unparking the loops.
        try:
            self._in_chan.write(_SENTINEL)
            self._out_chan.read(timeout=min(self._timeout, 5.0))
        except Exception:  # noqa: BLE001
            pass
        for ch in self._chan_of.values():
            ch.close()

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # noqa: BLE001
            pass


def compile_dag(output_node, **kwargs) -> CompiledDAG:
    return CompiledDAG(output_node, **kwargs)
