"""raylint — AST static analysis for distributed-runtime hazards.

The round-5 advisor audit (ADVICE.md) found latent defects by hand —
a blocking ``recv`` held under a mutex, a native handle nulled without
the lock guarding its dereference, a PRNG key dropped from checkpoint
state — and every one belongs to a mechanically detectable class. This
pass detects those classes over the ``ray_tpu`` tree the way the
reference codebase polices its runtime invariants with TSan builds and
``ray.util.inspect_serializability``.

Rules (registry below; ``raylint --list-rules`` prints this table):

- ``blocking-under-lock``     — a blocking call (``ray_tpu.get``,
  socket ``recv``, ``queue.get``, ``time.sleep``, ``Event.wait``)
  inside a ``with <lock>:`` body or an ``acquire()``/``release()``
  region. Blocking while holding a mutex serializes every other
  acquirer behind one I/O latency (the TaskClient::Wait bug class).
- ``unguarded-handle-teardown`` — ``self._h = None`` in a teardown
  method while other methods pass ``self._h`` into calls without a
  common lock (the PullManager use-after-free class).
- ``state-roundtrip-asymmetry`` — mutable algorithm state initialized
  in ``__init__``/``setup`` and reassigned during stepping, but absent
  from ``get_state`` (the LinearBandit dropped-PRNG-key class).
- ``naked-get-in-actor``      — ``ray_tpu.get(...)`` without
  ``timeout=`` inside a ``@remote`` actor method: a distributed
  deadlock hazard (actor A blocks forever on actor B blocking on A).
- ``unserializable-capture``  — a ``@remote`` function/class closing
  over a name bound to a known-unpicklable factory (``threading.Lock``,
  ``open``, ``socket.socket``, ...). The runtime diagnosis twin of this
  static rule is ``ray_tpu.util.check_serialize.inspect_serializability``,
  which walks a live object to the exact failing member.
- ``lock-order-inversion``    — two locks acquired in opposite nested
  orders across methods of one class (or one module's functions): a
  deadlock the moment both paths run concurrently.
- ``ref-leak-in-loop``        — a ``.remote(...)`` result appended to
  a list inside a ``while`` loop that never drains or bounds the list:
  every retained ObjectRef pins its object in the store, so a
  long-running producer loop fills the arena (the unbounded
  in-flight-refs class).
- ``await-under-lock``        — ``await`` inside a coroutine while a
  non-async lock's ``with`` block is held: the coroutine suspends
  mid-critical-section, so every other task on the loop (and every
  thread) contending that lock stalls behind an arbitrary-latency
  resume — and the loop deadlocks outright if the resume needs a task
  that is itself waiting on the lock. ``async with`` locks release
  cooperatively and are exempt.

Suppressions are per line, must name the rule, and must carry a
justification after ``--``::

    self._h = None  # raylint: disable=unguarded-handle-teardown -- single-owner shutdown, documented in the class docstring

A disable comment without a justification is itself reported
(``unjustified-suppression``) so the clean-tree gate in
``tests/test_lint_clean.py`` stays meaningful.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Findings + rule registry
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    suppressed: bool = False

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message, "suppressed": self.suppressed}

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


RULES: Dict[str, "Rule"] = {}


@dataclass
class Rule:
    name: str
    doc: str
    check: Callable[["FileContext"], List[Finding]]


def rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = Rule(name, doc, fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------

# Terminal-name patterns. "mu" is in the set because the C++ side's
# naming convention (mu_) leaks into bindings and tests.
_LOCKISH = re.compile(r"(^|_)(lock|locks|rlock|mutex|mu|guard)($|_)|lock$",
                      re.IGNORECASE)
# Condition variables acquire their lock as a context manager too, but
# `with cv: cv.wait()` RELEASES the lock while waiting — the canonical
# pattern, not a hazard. Names that look like CVs are not "locks" here.
_CVISH = re.compile(r"(^|_)(cv|cond|condition)($|_)", re.IGNORECASE)
_QUEUEISH = re.compile(r"(^|_)(q|queue|queues|inbox|mailbox)$",
                       re.IGNORECASE)
_EVENTISH = re.compile(r"event|(^|_)ev$", re.IGNORECASE)

_SOCKET_READS = {"recv", "recv_into", "recvfrom", "recvmsg", "accept"}

_RAY_MODULES = {"ray", "ray_tpu"}


def _terminal_name(expr: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute, or — for a call
    like ``self._lock.read()`` — the receiver's name, so guard-object
    factory methods still read as lock-ish."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Attribute):
            inner = _terminal_name(f.value)
            if inner is not None and _LOCKISH.search(inner):
                return inner
            return f.attr
        return _terminal_name(f)
    return None


def _lockish(expr: ast.AST) -> bool:
    name = _terminal_name(expr)
    return bool(name and _LOCKISH.search(name) and not _CVISH.search(name))


def _expr_key(expr: ast.AST) -> str:
    """Stable identity string for a lock expression (``self._lock``)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return f"{_expr_key(expr.value)}.{expr.attr}"
    if isinstance(expr, ast.Call):
        return f"{_expr_key(expr.func)}()"
    try:
        return ast.unparse(expr)
    except Exception:
        return repr(expr)


def _blocking_call(call: ast.Call) -> Optional[str]:
    """A human description when `call` is a known-blocking primitive."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv_name = _terminal_name(f.value) or ""
    if f.attr in _SOCKET_READS:
        return f"blocking socket {recv_name}.{f.attr}()"
    if f.attr == "sleep" and recv_name == "time":
        return "time.sleep()"
    if f.attr == "get" and recv_name in _RAY_MODULES:
        return f"{recv_name}.get()"
    if (f.attr == "get" and _QUEUEISH.search(recv_name)
            and not call.args):
        # positional args mean dict.get(key, default) — a queue-ish
        # NAME on a plain mapping must not count as blocking
        return f"queue {recv_name}.get()"
    if f.attr == "wait" and _EVENTISH.search(recv_name):
        return f"{recv_name}.wait()"
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)


def _calls_outside_nested_defs(node: ast.AST) -> Iterable[ast.Call]:
    """Call nodes in `node`, not descending into nested function/class
    definitions (those run in another context, with their own scan)."""
    stack = list(ast.iter_child_nodes(node))
    if isinstance(node, ast.Call):
        yield node
    while stack:
        n = stack.pop()
        if isinstance(n, _SKIP_NODES):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


@dataclass
class LockScan:
    """Everything one pass over a function body learns about locks."""
    # (lock_key, blocking description, call node)
    blocking: List[Tuple[str, str, ast.Call]]
    # (outer_key, inner_key, node) — inner acquired while outer held
    edges: List[Tuple[str, str, ast.AST]]
    # attr -> [(node, was_lock_held)] for `self.X = None` assignments
    null_assigns: Dict[str, List[Tuple[ast.AST, bool]]]
    # attr -> [(node, was_lock_held)] for `self.X` passed as a call arg
    handle_args: Dict[str, List[Tuple[ast.AST, bool]]]


def _scan_function(fn: ast.AST) -> LockScan:
    scan = LockScan([], [], {}, {})
    held: List[str] = []

    def record_acquire(key: str, node: ast.AST) -> None:
        for outer in held:
            if outer != key:
                scan.edges.append((outer, key, node))

    def note_self_none(stmt: ast.stmt) -> None:
        if not isinstance(stmt, ast.Assign):
            return
        if not (isinstance(stmt.value, ast.Constant)
                and stmt.value.value is None):
            return
        for tgt in stmt.targets:
            tgts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for t in tgts:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    scan.null_assigns.setdefault(t.attr, []).append(
                        (stmt, bool(held)))

    def note_handle_args(stmt: ast.stmt) -> None:
        for call in _calls_outside_nested_defs(stmt):
            argv = list(call.args) + [k.value for k in call.keywords]
            for a in argv:
                if isinstance(a, ast.Starred):
                    a = a.value
                if (isinstance(a, ast.Attribute)
                        and isinstance(a.value, ast.Name)
                        and a.value.id == "self"):
                    scan.handle_args.setdefault(a.attr, []).append(
                        (call, bool(held)))

    def scan_blocking(stmt: ast.stmt) -> None:
        if not held:
            return
        for call in _calls_outside_nested_defs(stmt):
            desc = _blocking_call(call)
            if desc:
                scan.blocking.append((held[-1], desc, call))

    def process(stmts: List[ast.stmt]) -> None:
        # Compound statements record only their HEADER expressions at
        # this level and recurse into bodies — running the recording
        # helpers on the whole subtree would log body sites a second
        # time with the pre-`with` held state.
        for stmt in stmts:
            note_self_none(stmt)
            if isinstance(stmt, _SKIP_NODES):
                continue
            # explicit acquire()/release() as statements
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                         ast.Call):
                c = stmt.value
                if (isinstance(c.func, ast.Attribute)
                        and c.func.attr in ("acquire", "release")
                        and _lockish(c.func.value)):
                    key = _expr_key(c.func.value)
                    if c.func.attr == "acquire":
                        record_acquire(key, c)
                        held.append(key)
                    elif key in held:
                        # remove the most recent acquisition
                        for i in range(len(held) - 1, -1, -1):
                            if held[i] == key:
                                del held[i]
                                break
                    continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                taken = []
                for item in stmt.items:
                    note_handle_args(item.context_expr)
                    if _lockish(item.context_expr):
                        key = _expr_key(item.context_expr)
                        record_acquire(key, item.context_expr)
                        held.append(key)
                        taken.append(key)
                    else:
                        scan_blocking(ast.Expr(value=item.context_expr)
                                      if isinstance(item.context_expr,
                                                    ast.Call)
                                      else stmt)
                process(stmt.body)
                for _ in taken:
                    held.pop()
                continue
            # compound statements: scan headers, recurse into bodies
            if isinstance(stmt, (ast.If, ast.While)):
                note_handle_args(stmt.test)
                scan_blocking(ast.Expr(value=stmt.test))
                process(stmt.body)
                process(stmt.orelse)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                note_handle_args(stmt.iter)
                scan_blocking(ast.Expr(value=stmt.iter))
                process(stmt.body)
                process(stmt.orelse)
                continue
            if isinstance(stmt, ast.Try):
                process(stmt.body)
                for h in stmt.handlers:
                    process(h.body)
                process(stmt.orelse)
                process(stmt.finalbody)
                continue
            note_handle_args(stmt)
            scan_blocking(stmt)

    body = getattr(fn, "body", [])
    process(body)
    return scan


def _functions(tree: ast.AST) -> List[ast.AST]:
    return [n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)]


def _is_remote_decorated(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name) and target.id == "remote":
            return True
        if isinstance(target, ast.Attribute) and target.attr == "remote":
            return True
    return False


def _attr_stores(fn: ast.AST) -> Dict[str, ast.AST]:
    """self.X assignment targets inside `fn` (first node per attr)."""
    out: Dict[str, ast.AST] = {}

    def note(t: ast.AST, node: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                note(e, node)
        elif isinstance(t, ast.Starred):
            note(t.value, node)
        elif (isinstance(t, ast.Attribute)
              and isinstance(t.value, ast.Name) and t.value.id == "self"):
            out.setdefault(t.attr, node)

    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                note(t, n)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            note(n.target, n)
    return out


# ---------------------------------------------------------------------------
# Per-file context: source, tree, suppressions
# ---------------------------------------------------------------------------

_DISABLE_RE = re.compile(
    r"#\s*raylint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(\S.*))?$")


class FileContext:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # line -> (set of rule names, has_justification)
        self.suppressions: Dict[int, Tuple[set, bool]] = {}
        self._parse_suppressions()
        self._scans: Optional[Dict[ast.AST, LockScan]] = None

    def _parse_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _DISABLE_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                    self.suppressions[tok.start[0]] = (
                        rules, m.group(2) is not None)
        except tokenize.TokenizeError:
            pass

    def lock_scans(self) -> Dict[ast.AST, LockScan]:
        if self._scans is None:
            self._scans = {fn: _scan_function(fn)
                           for fn in _functions(self.tree)}
        return self._scans

    def finding(self, node_or_line, rule_name: str,
                message: str) -> Finding:
        line = (node_or_line if isinstance(node_or_line, int)
                else getattr(node_or_line, "lineno", 0))
        return Finding(self.path, line, rule_name, message)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


@rule("blocking-under-lock",
      "blocking call (ray_tpu.get / socket recv / queue.get / "
      "time.sleep / Event.wait) while holding a lock")
def _check_blocking_under_lock(ctx: FileContext) -> List[Finding]:
    out = []
    for fn, scan in ctx.lock_scans().items():
        for lock_key, desc, call in scan.blocking:
            out.append(ctx.finding(
                call, "blocking-under-lock",
                f"{desc} while holding `{lock_key}` in "
                f"{getattr(fn, 'name', '<fn>')}(): every other acquirer "
                f"stalls behind this call's latency"))
    return out


@rule("lock-order-inversion",
      "two locks acquired in opposite nested orders across methods "
      "of one class/module")
def _check_lock_order(ctx: FileContext) -> List[Finding]:
    out = []
    scans = ctx.lock_scans()

    # group function nodes by owning class (None = module level)
    owner: Dict[ast.AST, Optional[str]] = {}

    def assign_owner(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                assign_owner(child, child.name)
            else:
                if isinstance(child, _FUNC_NODES):
                    owner[child] = cls
                assign_owner(child, cls)

    assign_owner(ctx.tree, None)

    groups: Dict[Optional[str], List[Tuple[str, str, ast.AST, str]]] = {}
    for fn, scan in scans.items():
        for a, b, node in scan.edges:
            groups.setdefault(owner.get(fn), []).append(
                (a, b, node, getattr(fn, "name", "<fn>")))

    for cls, edges in groups.items():
        seen = {(a, b) for a, b, _, _ in edges}
        reported = set()
        for a, b, node, fname in edges:
            if (b, a) in seen and (b, a) not in reported:
                reported.add((a, b))
                where = f"class {cls}" if cls else "module"
                out.append(ctx.finding(
                    node, "lock-order-inversion",
                    f"`{a}` then `{b}` acquired here ({fname}()), but "
                    f"the opposite order exists elsewhere in {where} — "
                    f"deadlock when both paths run concurrently"))
    return out


@rule("unguarded-handle-teardown",
      "self.<attr> nulled in a teardown method while other methods "
      "pass it into calls without a lock")
def _check_handle_teardown(ctx: FileContext) -> List[Finding]:
    out = []
    scans = ctx.lock_scans()
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body if isinstance(n, _FUNC_NODES)]
        nulls: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        uses: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        for m in methods:
            scan = scans.get(m)
            if scan is None:
                continue
            # `self.X = None` in __init__/setup is initialization, not
            # teardown; uses in __init__ precede any sharing — neither
            # side can race construction.
            if m.name not in _INIT_METHODS:
                for attr, sites in scan.null_assigns.items():
                    for node, under_lock in sites:
                        nulls.setdefault(attr, []).append(
                            (m.name, node, under_lock))
                for attr, sites in scan.handle_args.items():
                    for node, under_lock in sites:
                        uses.setdefault(attr, []).append(
                            (m.name, node, under_lock))
        for attr, null_sites in nulls.items():
            for tname, tnode, t_locked in null_sites:
                other = [(mname, node, locked)
                         for mname, node, locked in uses.get(attr, [])
                         if mname != tname]
                if not other:
                    continue
                unlocked_uses = [mname for mname, _, locked in other
                                 if not locked]
                if t_locked and not unlocked_uses:
                    continue  # both sides guarded
                using = sorted(set(m for m, _, _ in other))
                out.append(ctx.finding(
                    tnode, "unguarded-handle-teardown",
                    f"self.{attr} is nulled in {tname}() but passed "
                    f"into calls by {', '.join(using)}() — without a "
                    f"common lock a concurrent caller dereferences a "
                    f"freed handle (use-after-free)"))
                break  # one report per attr is enough
    return out


_INIT_METHODS = {"__init__", "setup", "__post_init__"}
_STATE_METHODS = {"get_state", "set_state"}


@rule("state-roundtrip-asymmetry",
      "mutable algorithm state missing from get_state/set_state")
def _check_state_roundtrip(ctx: FileContext) -> List[Finding]:
    out = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {n.name: n for n in cls.body
                   if isinstance(n, _FUNC_NODES)}
        if not _STATE_METHODS <= set(methods):
            continue
        get_state = methods["get_state"]
        # A get_state that defers to super()/helpers packs attrs we
        # cannot see statically — skip the class rather than guess.
        delegates = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            and n.func.id == "super"
            for n in ast.walk(get_state))
        if delegates:
            continue
        init_attrs = {}
        for name in _INIT_METHODS & set(methods):
            init_attrs.update(_attr_stores(methods[name]))
        mutated = {}
        for name, m in methods.items():
            if name in _INIT_METHODS | _STATE_METHODS:
                continue
            mutated.update(_attr_stores(m))
        get_names = {n.attr for n in ast.walk(get_state)
                     if isinstance(n, ast.Attribute)
                     and isinstance(n.value, ast.Name)
                     and n.value.id == "self"}
        get_keys = {n.value for n in ast.walk(get_state)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
        for attr in sorted(set(init_attrs) & set(mutated)):
            if attr.startswith("__"):
                continue
            if attr in get_names or attr in get_keys \
                    or attr.lstrip("_") in get_keys:
                continue
            out.append(ctx.finding(
                mutated[attr], "state-roundtrip-asymmetry",
                f"self.{attr} is initialized in "
                f"{'/'.join(sorted(_INIT_METHODS & set(methods)))} and "
                f"reassigned here, but {cls.name}.get_state() never "
                f"serializes it — a restored run diverges from the "
                f"original (the dropped-PRNG-key bug class)"))
    return out


@rule("naked-get-in-actor",
      "ray_tpu.get() without timeout= inside an actor method")
def _check_naked_get(ctx: FileContext) -> List[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not _is_remote_decorated(node):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if (isinstance(f, ast.Attribute) and f.attr == "get"
                    and (_terminal_name(f.value) or "") in _RAY_MODULES):
                if not any(k.arg == "timeout" for k in call.keywords):
                    out.append(ctx.finding(
                        call, "naked-get-in-actor",
                        "ray_tpu.get() without timeout= inside an actor "
                        "method: if the awaited task (transitively) "
                        "needs this actor, the cluster deadlocks — "
                        "pass timeout= and surface the failure"))
    return out


_UNPICKLABLE_FACTORIES = {
    ("threading", "Lock"), ("threading", "RLock"),
    ("threading", "Condition"), ("threading", "Event"),
    ("threading", "Semaphore"), ("threading", "BoundedSemaphore"),
    ("socket", "socket"), ("ctypes", "CDLL"),
    ("subprocess", "Popen"), ("sqlite3", "connect"),
    ("mmap", "mmap"), ("_thread", "allocate_lock"),
}
_UNPICKLABLE_BUILTINS = {"open"}


def _unpicklable_factory(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Name) and f.id in _UNPICKLABLE_BUILTINS:
        return f.id + "(...)"
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and (f.value.id, f.attr) in _UNPICKLABLE_FACTORIES):
        return f"{f.value.id}.{f.attr}()"
    return None


@rule("unserializable-capture",
      "@remote function/class capturing a known-unpicklable object")
def _check_unserializable_capture(ctx: FileContext) -> List[Finding]:
    out = []

    def scope_bindings(scope: ast.AST) -> Dict[str, str]:
        """name -> factory description for unpicklable assignments made
        directly in `scope` (not in nested defs)."""
        found: Dict[str, str] = {}
        body = getattr(scope, "body", None)
        if not isinstance(body, list):
            return found
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                desc = _unpicklable_factory(stmt.value)
                if desc:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            found[t.id] = desc
        return found

    def child_scopes(node: ast.AST) -> Iterable[ast.AST]:
        for c in ast.iter_child_nodes(node):
            if isinstance(c, _FUNC_NODES + (ast.ClassDef,)):
                yield c
            else:
                yield from child_scopes(c)

    def visit(scope: ast.AST, bindings: Dict[str, str]) -> None:
        merged = dict(bindings)
        merged.update(scope_bindings(scope))
        for child in child_scopes(scope):
            if _is_remote_decorated(child) and merged:
                loaded = {n.id for n in ast.walk(child)
                          if isinstance(n, ast.Name)
                          and isinstance(n.ctx, ast.Load)}
                for name in sorted(loaded & set(merged)):
                    out.append(ctx.finding(
                        child, "unserializable-capture",
                        f"@remote {child.name} captures `{name}` = "
                        f"{merged[name]}, which cloudpickle cannot "
                        f"serialize — task submission will fail at "
                        f"runtime (diagnose live objects with "
                        f"ray_tpu.util.check_serialize."
                        f"inspect_serializability)"))
            visit(child, merged)

    visit(ctx.tree, {})
    return out


def _is_remote_submit(value: ast.AST) -> bool:
    """True for ``f.remote(...)`` / ``f.options(...).remote(...)``."""
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "remote")


@rule("ref-leak-in-loop",
      "ObjectRefs appended to a list inside a `while` loop that never "
      "drains or bounds it")
def _check_ref_leak_in_loop(ctx: FileContext) -> List[Finding]:
    out = []
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, ast.While):
            continue
        # Names bound to `.remote(...)` results inside this loop body —
        # `r = f.remote(); refs.append(r)` leaks the same way the
        # direct `refs.append(f.remote())` does.
        remote_names = set()
        for n in ast.walk(loop):
            if isinstance(n, ast.Assign) and _is_remote_submit(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        remote_names.add(t.id)
        # A loop condition that reads the list bounds it (`while
        # len(refs) < k:` is an accumulate-to-target, not a leak).
        test_keys = {_expr_key(n) for n in ast.walk(loop.test)
                     if isinstance(n, (ast.Name, ast.Attribute))}
        appends: Dict[str, ast.AST] = {}
        drained = set()
        for n in ast.walk(loop):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                recv = _expr_key(n.func.value)
                if n.func.attr == "append" and n.args:
                    a = n.args[0]
                    if (_is_remote_submit(a)
                            or (isinstance(a, ast.Name)
                                and a.id in remote_names)):
                        appends.setdefault(recv, n)
                elif n.func.attr in ("pop", "popleft", "clear", "remove"):
                    drained.add(recv)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        drained.add(_expr_key(t.value))
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        # `refs[:k] = []` slice-drains in place
                        drained.add(_expr_key(t.value))
                    elif isinstance(t, (ast.Name, ast.Attribute)):
                        # `refs = refs[k:]` rebinding
                        drained.add(_expr_key(t))
        for recv, node in appends.items():
            if recv in drained or recv in test_keys:
                continue
            out.append(ctx.finding(
                node, "ref-leak-in-loop",
                f"`{recv}.append(<.remote() result>)` in a `while` loop "
                f"that never drains `{recv}` — every retained ref pins "
                f"its object in the store, so the arena fills for as "
                f"long as the loop runs; pop/slice consumed refs or "
                f"bound the loop on len({recv})"))
    return out


@rule("await-under-lock",
      "`await` inside a coroutine while a non-async lock's `with` "
      "block is held")
def _check_await_under_lock(ctx: FileContext) -> List[Finding]:
    out = []

    def awaits_in(node: ast.AST) -> Iterable[ast.Await]:
        # Nested defs/lambdas run in another context with their own
        # scan (coroutines among them get their own held=[] pass).
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, _SKIP_NODES):
                continue
            if isinstance(n, ast.Await):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def flag(node: ast.AST, held: List[str], fname: str) -> None:
        for aw in awaits_in(node):
            out.append(ctx.finding(
                aw, "await-under-lock",
                f"`await` while holding `{held[-1]}` in {fname}(): the "
                f"coroutine suspends mid-critical-section, so every "
                f"other task on the loop (and every thread) contending "
                f"the lock stalls until this resumes — release before "
                f"awaiting, or use an asyncio.Lock with `async with`"))

    def process(stmts: List[ast.stmt], held: List[str],
                fname: str) -> None:
        # Mirrors _scan_function's traversal: compound statements scan
        # only their HEADER expressions at this level and recurse into
        # bodies, so each await is judged against the lock state that
        # is actually in effect where it runs.
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            if isinstance(stmt, ast.With):
                taken = []
                for item in stmt.items:
                    if held:
                        flag(item.context_expr, held, fname)
                    if _lockish(item.context_expr):
                        key = _expr_key(item.context_expr)
                        held.append(key)
                        taken.append(key)
                process(stmt.body, held, fname)
                for _ in taken:
                    held.pop()
                continue
            if isinstance(stmt, ast.AsyncWith):
                # An async lock releases cooperatively across its
                # awaits — holding one is not the hazard. Awaits in
                # the body still count against any OUTER sync lock.
                process(stmt.body, held, fname)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                if held:
                    flag(stmt.test, held, fname)
                process(stmt.body, held, fname)
                process(stmt.orelse, held, fname)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                if held:
                    flag(stmt.iter, held, fname)
                process(stmt.body, held, fname)
                process(stmt.orelse, held, fname)
                continue
            if isinstance(stmt, ast.Try):
                process(stmt.body, held, fname)
                for h in stmt.handlers:
                    process(h.body, held, fname)
                process(stmt.orelse, held, fname)
                process(stmt.finalbody, held, fname)
                continue
            if held:
                flag(stmt, held, fname)

    for fn in _functions(ctx.tree):
        if isinstance(fn, ast.AsyncFunctionDef):
            process(fn.body, [], fn.name)
    return out


_METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# inventory path -> set of names (None sentinel cached for "no file")
_METRIC_INVENTORY_CACHE: Dict[str, Optional[set]] = {}


def _metrics_inventory(start: str) -> Optional[set]:
    """Registered metric names: every backticked identifier in the
    nearest ``docs/METRICS.md`` walking up from the linted file. None
    when no inventory exists (rule stays silent — an installed copy of
    the package without docs/ must not fail)."""
    d = os.path.dirname(os.path.abspath(start))
    while True:
        cand = os.path.join(d, "docs", "METRICS.md")
        if cand in _METRIC_INVENTORY_CACHE:
            got = _METRIC_INVENTORY_CACHE[cand]
            if got is not None:
                return got
        elif os.path.isfile(cand):
            try:
                with open(cand) as f:
                    names = {m for m in re.findall(r"`([a-z0-9_]+)`",
                                                   f.read())
                             if _METRIC_NAME_RE.match(m)}
            except OSError:
                names = set()
            _METRIC_INVENTORY_CACHE[cand] = names
            return names
        else:
            _METRIC_INVENTORY_CACHE[cand] = None
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


@rule("metric-name-registry",
      "Counter/Gauge/Histogram registered under a name missing from "
      "the docs/METRICS.md inventory")
def _check_metric_name_registry(ctx: FileContext) -> List[Finding]:
    """An unregistered metric name is a dashboard nobody will find:
    Grafana panels, the metrics-history CLI, and operators grep the
    inventory, not the source. Every constructor call with a constant
    name must have that name in the checked-in table."""
    inventory = _metrics_inventory(ctx.path)
    if inventory is None:
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal_name(node.func) not in _METRIC_CLASSES:
            continue
        # Discriminate against collections.Counter(iterable): the
        # metrics API always carries a description — a second
        # positional string or a description= keyword.
        has_desc = (
            len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)) or any(
                kw.arg == "description" for kw in node.keywords)
        if not has_desc or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        name = first.value
        if not _METRIC_NAME_RE.match(name):
            continue
        if name not in inventory:
            out.append(ctx.finding(
                node, "metric-name-registry",
                f"metric `{name}` is not in the docs/METRICS.md "
                f"inventory — add a row (name, type, tags, meaning) "
                f"so dashboards and the obs CLI can find it"))
    return out


_LINTS_DOC_CACHE: Dict[str, Optional[set]] = {}


def _lints_inventory(start: str) -> Optional[set]:
    """Documented rule ids: every backticked kebab-case identifier in
    the nearest ``docs/LINTS.md`` walking up from the linted file.
    None when no inventory exists (the meta-rule stays silent — an
    installed copy of the package without docs/ must not fail)."""
    d = os.path.dirname(os.path.abspath(start))
    while True:
        cand = os.path.join(d, "docs", "LINTS.md")
        if cand in _LINTS_DOC_CACHE:
            got = _LINTS_DOC_CACHE[cand]
            if got is not None:
                return got
        elif os.path.isfile(cand):
            try:
                with open(cand) as f:
                    ids = set(re.findall(r"`([a-z][a-z0-9\-]+)`",
                                         f.read()))
            except OSError:
                ids = set()
            _LINTS_DOC_CACHE[cand] = ids
            return ids
        else:
            _LINTS_DOC_CACHE[cand] = None
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


@rule("rule-doc-registry",
      "a registered lint rule id missing from the docs/LINTS.md "
      "inventory")
def _check_rule_doc_registry(ctx: FileContext) -> List[Finding]:
    """An undocumented rule is a finding nobody can act on: the doc
    carries the rationale and the suppression recipe. Anchored to the
    registry module so it fires exactly once per tree lint."""
    if not ctx.path.replace(os.sep, "/").endswith(
            "devtools/raylint.py"):
        return []
    inventory = _lints_inventory(ctx.path)
    if inventory is None:
        return []
    from .xp import XP_RULES
    registered = set(RULES) | set(XP_RULES) | {
        "unjustified-suppression", "parse-error"}
    missing = sorted(registered - inventory)
    if not missing:
        return []
    return [ctx.finding(
        1, "rule-doc-registry",
        f"rule id(s) missing from docs/LINTS.md: "
        f"{', '.join(missing)} — every registered rule needs a row "
        f"(id, severity, rationale, example, suppression recipe)")]


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def iter_python_files(paths: Iterable[str]) -> List[str]:
    files = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        else:
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(root, n)
                             for n in sorted(names)
                             if n.endswith(".py"))
    return sorted(set(files))


def lint_file(path: str,
              select: Optional[Iterable[str]] = None) -> List[Finding]:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        ctx = FileContext(path, source)
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return [Finding(path, getattr(e, "lineno", 0) or 0,
                        "parse-error", str(e))]
    findings: List[Finding] = []
    names = list(select) if select else list(RULES)
    for name in names:
        findings.extend(RULES[name].check(ctx))
    # apply suppressions + demand justifications
    for f in findings:
        supp = ctx.suppressions.get(f.line)
        if supp and (f.rule in supp[0] or "*" in supp[0]):
            f.suppressed = True
    for line, (rules, justified) in sorted(ctx.suppressions.items()):
        if not justified:
            findings.append(Finding(
                path, line, "unjustified-suppression",
                f"raylint suppression of {', '.join(sorted(rules))} "
                f"carries no `-- <justification>`: every disable must "
                f"say why the hazard does not apply"))
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def lint_paths(paths: Iterable[str],
               select: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, select))
    return findings


def changed_files(paths: Iterable[str], base: str) -> Optional[set]:
    """Absolute paths of files changed vs `base` (plus untracked
    files) in the git repo containing the first lint path; None when
    git is unavailable or the tree is not a repo."""
    import subprocess
    anchor = os.path.abspath(next(iter(paths), "."))
    if not os.path.isdir(anchor):
        anchor = os.path.dirname(anchor)
    try:
        top = subprocess.run(
            ["git", "-C", anchor, "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        out: set = set()
        for cmd in (["diff", "--name-only", base],
                    ["ls-files", "--others", "--exclude-standard"]):
            got = subprocess.run(
                ["git", "-C", root] + cmd,
                capture_output=True, text=True, timeout=30)
            if got.returncode != 0:
                return None
            out |= {os.path.abspath(os.path.join(root, line.strip()))
                    for line in got.stdout.splitlines() if line.strip()}
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _render_stats(xp_stats: Optional[dict],
                  findings: List[Finding]) -> str:
    """The one-line CI summary behind --stats."""
    per_file = [0, 0]
    for f in findings:
        if f.rule in RULES or f.rule in ("unjustified-suppression",
                                         "parse-error"):
            per_file[1 if f.suppressed else 0] += 1
    parts = [f"per-file {per_file[0]} finding(s) "
             f"({per_file[1]} suppressed)"]
    if xp_stats is not None:
        from .xp import ANALYSIS_RULES
        head = (f"{xp_stats.get('files', 0)} files indexed, "
                f"{xp_stats.get('call_edges', 0)} call edges")
        if "cxx_files" in xp_stats:
            head += (f", {xp_stats['cxx_files']} C++ file(s) "
                     f"({xp_stats.get('cxx_exports', 0)} exports)")
        if "graph_entries" in xp_stats:
            head += (f", {xp_stats['graph_entries']} graph entry "
                     f"point(s) ({xp_stats.get('graph_nodes', 0)} "
                     f"nodes, {xp_stats.get('graph_edges', 0)} edges "
                     f"captured)")
        parts.insert(0, head)
        owner = {r: a for a, rs in ANALYSIS_RULES.items() for r in rs}
        per: Dict[str, List[int]] = {}
        for f in findings:
            a = owner.get(f.rule)
            if a is not None:
                per.setdefault(a, [0, 0])[1 if f.suppressed else 0] += 1
        for a in sorted(xp_stats.get("analyses", {})):
            act, sup = per.get(a, [0, 0])
            parts.append(f"{a} {act} finding(s) ({sup} suppressed)")
    return "raylint --stats: " + "; ".join(parts)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="raylint",
        description="static analysis for distributed-runtime hazards")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: the "
                         "installed ray_tpu package)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule names to run")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON report "
                         "(same as --format json)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default=None, dest="fmt",
                    help="report format (default: text)")
    ap.add_argument("--xp", action="store_true",
                    help="also run the whole-program passes "
                         "(cross-file lock-order, wire-protocol "
                         "conformance)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON for whole-program findings "
                         "(default with --xp: the checked-in "
                         "devtools/xp/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the default baseline")
    ap.add_argument("--proto-inventory", action="store_true",
                    help="print the wire-protocol inventory table "
                         "(implies --xp) and exit")
    ap.add_argument("--graph-out", default=None, metavar="PATH",
                    help="write the per-entry-point captured task "
                         "graphs (JSON) to this path (implies --xp)")
    ap.add_argument("--out", default=None,
                    help="write the report to this file instead of "
                         "stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in the report")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="BASE",
                    help="restrict findings to files changed vs BASE "
                         "(git diff --name-only; default HEAD) — the "
                         "whole program is still indexed, so "
                         "cross-file findings in changed files "
                         "remain visible")
    ap.add_argument("--stats", action="store_true",
                    help="print an index/analysis summary line to "
                         "stderr (files indexed, call edges, "
                         "per-analysis finding/suppression counts)")
    args = ap.parse_args(argv)

    if args.list_rules:
        from .xp import XP_RULES
        for name, r in sorted(RULES.items()):
            print(f"{name:28s} {r.doc}")
        for name, doc in sorted(XP_RULES.items()):
            print(f"{name:28s} [xp] {doc}")
        return 0

    paths = args.paths
    if not paths:
        paths = [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]
    run_xp_passes = (args.xp or args.proto_inventory
                     or args.graph_out is not None)
    select = None
    if args.select:
        from .xp import XP_RULES
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in select
                   if s not in RULES and s not in XP_RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    changed = None
    if args.changed_only is not None:
        changed = changed_files(paths, args.changed_only)
        if changed is None:
            print("raylint: --changed-only: git diff unavailable; "
                  "linting everything", file=sys.stderr)
        elif run_xp_passes and not args.select:
            print("raylint: --changed-only: graph analyses "
                  "(lockgraph/protocol/cross-language) deferred to "
                  "the full run; pass --select to force them",
                  file=sys.stderr)

    per_file_select = ([s for s in select if s in RULES]
                       if select else None)
    if select and not per_file_select:
        findings = []
    else:
        lint_inputs = paths
        if changed is not None:
            lint_inputs = [p for p in iter_python_files(paths)
                           if os.path.abspath(p) in changed]
        findings = lint_paths(lint_inputs, per_file_select)
    inventory = None
    xp_stats = {} if args.stats else None
    if run_xp_passes:
        from .xp import (XP_RULES, apply_baseline,
                         default_baseline_path, run_xp)
        graphs = [] if args.graph_out else None
        xp_findings, inventory = run_xp(paths, select, stats=xp_stats,
                                        only=changed, graphs=graphs)
        findings.extend(xp_findings)
        if args.graph_out:
            with open(args.graph_out, "w", encoding="utf-8") as fh:
                json.dump({"version": 1, "entries": graphs}, fh,
                          indent=2)
                fh.write("\n")
        baseline = args.baseline
        if baseline is None and not args.no_baseline:
            baseline = default_baseline_path()
        if baseline:
            findings.extend(apply_baseline(findings, baseline))
    if changed is not None:
        # whole-program passes still indexed everything; the REPORT is
        # what narrows to the diff. stale-baseline rows are dropped
        # outright: with the graph analyses deferred, their findings
        # are absent and every graph-rule entry would read as stale —
        # baseline hygiene belongs to full runs, not pre-commit.
        findings = [f for f in findings
                    if os.path.abspath(f.path) in changed
                    and f.rule != "stale-baseline"]
    if args.stats:
        print(_render_stats(xp_stats if run_xp_passes else None,
                            findings), file=sys.stderr)

    if args.proto_inventory:
        from .xp.report import inventory_table
        out = inventory_table(inventory or [])
        if args.out:
            with open(args.out, "w", encoding="utf-8") as fh:
                fh.write(out + "\n")
        else:
            print(out)
        return 0

    fmt = args.fmt or ("json" if args.as_json else "text")
    active = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else active
    if fmt == "json":
        from .xp.report import to_json
        report = to_json(shown if args.show_suppressed else active,
                         inventory)
        # keep totals over ALL findings, not just the shown subset
        payload = json.loads(report)
        payload["total"] = len(active)
        payload["suppressed"] = sum(
            1 for f in findings if f.suppressed)
        report = json.dumps(payload, indent=2)
    elif fmt == "sarif":
        from .xp import XP_RULES
        from .xp.report import to_sarif
        docs = {name: r.doc for name, r in RULES.items()}
        docs.update(XP_RULES)
        docs["unjustified-suppression"] = (
            "a raylint disable comment without a justification")
        report = to_sarif(findings, docs)
    else:
        lines = [f.render() for f in shown]
        lines.append(
            f"raylint: {len(active)} finding(s), "
            f"{sum(1 for f in findings if f.suppressed)} suppressed")
        report = "\n".join(lines)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(report + "\n")
    else:
        print(report)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
