"""Wire-protocol conformance: senders vs handlers of typed messages.

The control plane speaks untyped dicts — ``{"type": "task", ...}``
framed over TCP (driver <-> daemon) and unix sockets (daemon/driver
<-> worker). Nothing checks statically that both ends agree, and a
disagreement surfaces as a hang or a silently-dead protocol arm, not
a crash. This pass extracts both ends from the AST:

**Send sites** — a dict literal with a constant ``"type"`` key that
flows into a send-like call (``send_msg``, ``_send_json``,
``request``, ``call``, ``run_task``, ...): directly as an argument,
via a local variable (tracking ``msg["k"] = v`` field augmentation),
via a function that returns the message and a caller that sends the
result (the daemon's ``reply = self._handle_profile(msg)`` then
``send_msg(conn, reply)`` two-step), or via a parameter of a helper
that itself forwards into a send call (the dashboard's
``_daemon_call(node, {...})``).

**Handlers** — any comparison/membership test/match of a message's
``"type"`` against string constants, including through an alias
variable (``mtype = msg.get("type")``) and through a callee parameter
(``_dispatch_one(conn, msg, mtype, ...)`` dispatching on a type
computed by its caller).

**Field reads** — inside a handler branch for type ``T``, hard reads
``msg["k"]`` / ``msg.pop("k")`` (KeyError on absence) are demanded of
``T``'s senders; ``.get()``-style reads are tolerant. Reads made by
helpers the message is forwarded to are attributed through the call
graph. Any ``v["k"] = ...`` augmentation on a variable whose message
type is not statically known counts as potentially providing ``k``
(the daemon injects ``msg["fn"]`` into a relayed task this way), so
the missing-field rule only fires when *no* code path can provide
the field.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .index import FuncInfo, ProjectIndex

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)

# Terminal callable names that put a dict on a wire/queue toward
# another process. Wrappers reached through parameter forwarding are
# discovered automatically; this is the primitive set.
SEND_FUNCS = {"send_msg", "_send_json", "send_json", "request",
              "call", "run_task", "send"}

# Dispatch-socket ops with a second implementation in the native C++
# front end (src/node_dispatch.cc), so the inventory stays honest
# about which plane can answer when RAY_TPU_NATIVE_DISPATCH=1. Keyed
# by message type; the value names what the native loop does with it.
# The key set is no longer trusted: ffi.check_protocol derives the
# native dispatch surface from the C++ sources and reports any key
# here that drifted from it (stale) or any native arm this dict
# misses (xp-xlang-protocol).
NATIVE_PLANE = {
    "ping": "handled off-GIL (pong written natively unless tracing)",
    "pong": "sent natively with live ledger availability spliced in",
    "task": "admission header parsed; check-and-charge + spillback "
            "refusal natively; plain tasks handed straight to an "
            "idle worker's socket (zero daemon-side Python), others "
            "to Python on admission",
    "result": "spillback refusals, worker-death crash replies, and "
              "natively handed-off task results written natively "
              "(retry_at from the pushed peer digest)",
    "gen_ack": "framed natively, routed to the owning stream's "
               "drainer without per-handler timing",
    "pull_complete": "framed natively without per-handler timing",
    "dispatch_timing": "wall-clock dispatch stamps for a warm task "
                       "(admission arrival, worker write, reply "
                       "forward) sent ahead of the result frame when "
                       "the admission header carried tm",
}


@dataclass
class MsgLit:
    type: str
    fields: Set[str]
    path: str
    line: int
    sent: bool = False


# env/arg descriptors:
#   ("lit", MsgLit)          a tracked message literal
#   ("param", name)          the enclosing function's parameter
#   ("call", qual|None, i)   slot i of a call result
#   ("name", varname)        an untracked variable


@dataclass
class CallEvent:
    callee: Optional[str]          # resolved qual
    callee_is_method: bool
    terminal: str
    args: List[tuple]              # positional descs
    kwargs: Dict[str, tuple]
    arg_names: Dict[int, str]      # pos -> raw var name
    constraints: Dict[int, FrozenSet[str]]   # pos -> active types
    alias_pairs: List[Tuple[int, int]]       # (msg pos, type pos)
    line: int


@dataclass
class FnScan:
    fi: FuncInfo
    roles: Tuple[Tuple[str, str], ...]       # ((typeparam, msgparam),)
    literals: List[MsgLit] = field(default_factory=list)
    # var -> type|'*' -> field -> (line, hard)
    reads: Dict[str, Dict[str, Dict[str, Tuple[int, bool]]]] = \
        field(default_factory=dict)
    handled: List[Tuple[str, int]] = field(default_factory=list)
    sends: List[tuple] = field(default_factory=list)      # descs
    calls: List[CallEvent] = field(default_factory=list)
    returns: List[List[tuple]] = field(default_factory=list)
    provided_any: Set[str] = field(default_factory=set)


def _type_expr_var(node: ast.AST) -> Optional[str]:
    """var name when `node` reads var's "type" field."""
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and isinstance(node.slice, ast.Constant)
            and node.slice.value == "type"):
        return node.value.id
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop")
            and isinstance(node.func.value, ast.Name)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "type"):
        return node.func.value.id
    return None


def _const_types(node: ast.AST) -> Optional[FrozenSet[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset((node.value,))
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return frozenset(vals)
    return None


def _msg_literal(node: ast.AST, path: str) -> Optional[MsgLit]:
    if not isinstance(node, ast.Dict):
        return None
    mtype, fields = None, set()
    for k, v in zip(node.keys, node.values):
        if k is None or not (isinstance(k, ast.Constant)
                             and isinstance(k.value, str)):
            continue
        fields.add(k.value)
        if k.value == "type":
            if not (isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                return None          # dynamic type: not a wire literal
            mtype = v.value
    if mtype is None:
        return None
    return MsgLit(mtype, fields, path, node.lineno)


class _Walker:
    def __init__(self, idx: ProjectIndex, fi: FuncInfo,
                 roles: Tuple[Tuple[str, str], ...]):
        self.idx = idx
        self.fi = fi
        self.out = FnScan(fi, roles)
        self.env: Dict[str, tuple] = {
            p: ("param", p) for p in fi.param_names()}
        self.alias: Dict[str, str] = dict(
            (tp, mp) for tp, mp in roles)

    # -- recording ----------------------------------------------------

    def _read(self, var: str, fld: str, line: int, hard: bool,
              constraints: Dict[str, FrozenSet[str]]) -> None:
        if fld == "type":
            return
        slots = self.out.reads.setdefault(var, {})
        keys = constraints.get(var) or ("*",)
        for t in keys:
            slots.setdefault(t, {}).setdefault(fld, (line, hard))

    def _desc(self, node: ast.AST) -> Optional[tuple]:
        lit = _msg_literal(node, self.fi.path)
        if lit is not None:
            self.out.literals.append(lit)
            return ("lit", lit)
        if isinstance(node, ast.Name):
            return self.env.get(node.id, ("name", node.id))
        return None

    # -- expression scan ----------------------------------------------

    def scan_expr(self, node: ast.AST,
                  constraints: Dict[str, FrozenSet[str]]) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            # lambdas stay in the enclosing dataflow (they close over
            # the same message vars: the dashboard ships its request
            # through `lambda: node.client.call(msg)`); real nested
            # defs get their own scan.
            if n is None or isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
                continue
            if (isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, ast.Load)
                    and isinstance(n.value, ast.Name)
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, str)):
                self._read(n.value.id, n.slice.value, n.lineno,
                           True, constraints)
            elif isinstance(n, ast.Compare) and len(n.ops) == 1:
                # ANY comparison of a message's type against string
                # constants is dispatch — `ok = m.get("type") == "x"`
                # counts the same as `if m.get("type") == "x":`.
                if self._test_var(n.left) is not None:
                    ts = _const_types(n.comparators[0])
                    if ts is not None:
                        for t in sorted(ts):
                            self.out.handled.append((t, n.lineno))
            elif isinstance(n, ast.Call):
                self._on_call(n, constraints)
            stack.extend(ast.iter_child_nodes(n))

    def _on_call(self, call: ast.Call,
                 constraints: Dict[str, FrozenSet[str]]) -> None:
        f = call.func
        if (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.attr in ("get", "pop", "setdefault")
                and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            hard = (f.attr == "pop" and len(call.args) == 1
                    and not call.keywords)
            self._read(f.value.id, call.args[0].value, call.lineno,
                       hard, constraints)
            return
        terminal = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else "")
        resolved = self.idx.resolve_call(f, self.fi)
        args, arg_names, cons = [], {}, {}
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                args.append(None)
                continue
            args.append(self._desc(a))
            if isinstance(a, ast.Name):
                arg_names[i] = a.id
                if a.id in constraints:
                    cons[i] = constraints[a.id]
        kwargs = {}
        for kw in call.keywords:
            if kw.arg is not None:
                d = self._desc(kw.value)
                if d is not None:
                    kwargs[kw.arg] = d
        alias_pairs = []
        for i, name in arg_names.items():
            m = self.alias.get(name)
            if m is None:
                continue
            for j, mname in arg_names.items():
                if mname == m and j != i:
                    alias_pairs.append((j, i))
        if terminal in SEND_FUNCS:
            for d in list(args) + list(kwargs.values()):
                if d is not None and d[0] in ("lit", "param", "call"):
                    self.out.sends.append(d)
        self.out.calls.append(CallEvent(
            resolved.qual if resolved else None,
            bool(resolved and resolved.cls is not None
                 and isinstance(f, ast.Attribute)),
            terminal, args, kwargs, arg_names, cons, alias_pairs,
            call.lineno))

    # -- statement walk -----------------------------------------------

    def _bind_assign(self, stmt: ast.Assign,
                     constraints: Dict[str, FrozenSet[str]]) -> None:
        value = stmt.value
        lit = _msg_literal(value, self.fi.path)
        tvar = _type_expr_var(value)
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                self.alias.pop(tgt.id, None)
                if lit is not None:
                    self.out.literals.append(lit)
                    self.env[tgt.id] = ("lit", lit)
                elif tvar is not None:
                    self.alias[tgt.id] = tvar
                    self.env.pop(tgt.id, None)
                elif isinstance(value, ast.Call):
                    r = self.idx.resolve_call(value.func, self.fi)
                    self.env[tgt.id] = (
                        "call", r.qual if r else None, 0)
                else:
                    self.env.pop(tgt.id, None)
            elif (isinstance(tgt, ast.Tuple)
                    and isinstance(value, ast.Call)):
                r = self.idx.resolve_call(value.func, self.fi)
                for i, e in enumerate(tgt.elts):
                    if isinstance(e, ast.Name):
                        self.alias.pop(e.id, None)
                        self.env[e.id] = (
                            "call", r.qual if r else None, i)
            elif (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)):
                d = self.env.get(tgt.value.id)
                if d is not None and d[0] == "lit":
                    d[1].fields.add(tgt.slice.value)
                else:
                    self.out.provided_any.add(tgt.slice.value)

    def _type_test(self, test: ast.AST,
                   ) -> Optional[Tuple[str, FrozenSet[str], bool]]:
        """(msg var, types, positive) for a type-dispatch test.
        (Handled-type recording happens in scan_expr, which sees
        every comparison including these.)"""
        if isinstance(test, ast.BoolOp):
            found = None
            for v in test.values:
                r = self._type_test(v)
                if r is None:
                    continue
                if found is None:
                    found = r
                elif (isinstance(test.op, ast.Or)
                        and r[0] == found[0] and r[2] and found[2]):
                    found = (found[0], found[1] | r[1], True)
            if found is not None and isinstance(test.op, ast.Or):
                return found
            # `t == "x" and <more>` still narrows the branch
            return found
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
            return None
        var = self._test_var(test.left)
        if var is None:
            return None
        types = _const_types(test.comparators[0])
        if types is None:
            return None
        positive = isinstance(test.ops[0], (ast.Eq, ast.In))
        return (var, types, positive)

    def _test_var(self, left: ast.AST) -> Optional[str]:
        if isinstance(left, ast.Name):
            return self.alias.get(left.id)
        return _type_expr_var(left)

    def process(self, stmts: List[ast.stmt],
                constraints: Dict[str, FrozenSet[str]]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            if isinstance(stmt, ast.Assign):
                self.scan_expr(stmt.value, constraints)
                self._bind_assign(stmt, constraints)
                continue
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    self.scan_expr(stmt.value, constraints)
                continue
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self.scan_expr(stmt.value, constraints)
                    if isinstance(stmt.value, ast.Tuple):
                        slots = [self._desc(e)
                                 for e in stmt.value.elts]
                    else:
                        slots = [self._desc(stmt.value)]
                    self.out.returns.append(slots)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                tt = self._type_test(stmt.test)
                self.scan_expr(stmt.test, constraints)
                inner = dict(constraints)
                if tt is not None and tt[2]:
                    inner[tt[0]] = tt[1]
                self.process(stmt.body, inner)
                self.process(stmt.orelse, constraints)
                continue
            if isinstance(stmt, ast.Match):
                var = self._test_var(stmt.subject)
                if var is None:
                    var = _type_expr_var(stmt.subject)
                self.scan_expr(stmt.subject, constraints)
                for case in stmt.cases:
                    inner = dict(constraints)
                    if (var is not None
                            and isinstance(case.pattern,
                                           ast.MatchValue)):
                        ts = _const_types(case.pattern.value)
                        if ts is not None:
                            for t in sorted(ts):
                                self.out.handled.append(
                                    (t, case.pattern.value.lineno))
                            inner[var] = ts
                    self.process(case.body, inner)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.scan_expr(stmt.iter, constraints)
                self.process(stmt.body, constraints)
                self.process(stmt.orelse, constraints)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self.scan_expr(item.context_expr, constraints)
                self.process(stmt.body, constraints)
                continue
            if isinstance(stmt, ast.Try):
                self.process(stmt.body, constraints)
                for h in stmt.handlers:
                    self.process(h.body, constraints)
                self.process(stmt.orelse, constraints)
                self.process(stmt.finalbody, constraints)
                continue
            for child in ast.iter_child_nodes(stmt):
                self.scan_expr(child, constraints)

    def run(self) -> FnScan:
        self.process(list(getattr(self.fi.node, "body", [])), {})
        return self.out


# ---------------------------------------------------------------------
# Whole-program resolution
# ---------------------------------------------------------------------


class _Analyzer:
    def __init__(self, idx: ProjectIndex):
        self.idx = idx
        self.scans: Dict[Tuple[str, tuple], FnScan] = {}
        self._reads_memo: Dict[tuple, dict] = {}

    def scan(self, fi: FuncInfo,
             roles: Tuple[Tuple[str, str], ...] = ()) -> FnScan:
        key = (fi.qual, roles)
        if key not in self.scans:
            self.scans[key] = _Walker(self.idx, fi, roles).run()
        return self.scans[key]

    def _callee_param(self, ev: CallEvent,
                      pos: int) -> Optional[Tuple[FuncInfo, str]]:
        fi = self.idx.functions.get(ev.callee or "")
        if fi is None:
            return None
        params = fi.param_names()
        idx = pos + (1 if ev.callee_is_method else 0)
        if idx >= len(params):
            return None
        return fi, params[idx]

    def run(self):
        # phase 1: base scans + role scans via a worklist
        work = [(fi, ()) for fi in self.idx.all_functions()]
        seen = set()
        while work:
            fi, roles = work.pop()
            if (fi.qual, roles) in seen:
                continue
            seen.add((fi.qual, roles))
            s = self.scan(fi, roles)
            for ev in s.calls:
                for mpos, tpos in ev.alias_pairs:
                    got_m = self._callee_param(ev, mpos)
                    got_t = self._callee_param(ev, tpos)
                    if got_m is None or got_t is None:
                        continue
                    cfi, mparam = got_m
                    _, tparam = got_t
                    work.append((cfi, ((tparam, mparam),)))

        # phase 2: which (fn, param) forward into a send call
        sent_params: Set[Tuple[str, str]] = set()
        changed = True
        while changed:
            changed = False
            for (qual, roles), s in self.scans.items():
                for d in s.sends:
                    if d[0] == "param" and (qual, d[1]) not in \
                            sent_params:
                        sent_params.add((qual, d[1]))
                        changed = True
                for ev in s.calls:
                    items = list(enumerate(ev.args))
                    for pos, d in items:
                        if d is None or d[0] != "param":
                            continue
                        got = self._callee_param(ev, pos)
                        if got is None:
                            continue
                        cfi, pname = got
                        if ((cfi.qual, pname) in sent_params
                                and (qual, d[1]) not in sent_params):
                            sent_params.add((qual, d[1]))
                            changed = True

        # phase 3: mark literals as sent (3 passes resolve
        # literal -> returned -> sent-by-caller chains)
        returns: Dict[str, List[List[tuple]]] = {}
        for (qual, roles), s in self.scans.items():
            if not roles:
                returns.setdefault(qual, []).extend(s.returns)

        def mark(d: Optional[tuple], depth: int = 0) -> None:
            if d is None or depth > 3:
                return
            if d[0] == "lit":
                d[1].sent = True
            elif d[0] == "call":
                for slots in returns.get(d[1] or "", []):
                    if d[2] < len(slots):
                        mark(slots[d[2]], depth + 1)

        for _ in range(2):
            for (qual, roles), s in self.scans.items():
                for d in s.sends:
                    mark(d)
                for ev in s.calls:
                    for pos, d in enumerate(ev.args):
                        got = self._callee_param(ev, pos)
                        if got and (got[0].qual, got[1]) in \
                                sent_params:
                            mark(d)
                    for kwname, d in ev.kwargs.items():
                        fi = self.idx.functions.get(ev.callee or "")
                        if fi and (fi.qual, kwname) in sent_params:
                            mark(d)

        # phase 4: global read/handled/sent aggregation
        handled: Dict[str, List[Tuple[str, int]]] = {}
        reads: Dict[str, Dict[str, Tuple[str, int, bool]]] = {}
        senders: Dict[str, List[MsgLit]] = {}
        provided_any: Set[str] = set()
        lit_seen: Set[Tuple[str, int, str]] = set()

        def add_reads(t: str, fields: Dict[str, Tuple[int, bool]],
                      path: str) -> None:
            if t == "*":
                return
            slot = reads.setdefault(t, {})
            for fld, (line, hard) in fields.items():
                prev = slot.get(fld)
                if prev is None or (hard and not prev[2]):
                    slot[fld] = (path, line, hard)

        for (qual, roles), s in self.scans.items():
            provided_any |= s.provided_any
            for t, line in s.handled:
                handled.setdefault(t, []).append((s.fi.path, line))
            for lit in s.literals:
                if not lit.sent:
                    continue
                key = (lit.path, lit.line, lit.type)
                if key in lit_seen:
                    continue
                lit_seen.add(key)
                senders.setdefault(lit.type, []).append(lit)
            for var, by_type in s.reads.items():
                for t, fields in by_type.items():
                    add_reads(t, fields, s.fi.path)
            # forward constrained message vars into callee reads
            for ev in s.calls:
                for pos, ts in ev.constraints.items():
                    got = self._callee_param(ev, pos)
                    if got is None:
                        continue
                    cfi, pname = got
                    child = self.param_reads(cfi, pname, ())
                    for ct, fields in child.items():
                        if ct == "*":
                            for t in ts:
                                add_reads(t, fields, cfi.path)
                        else:
                            add_reads(ct, fields, cfi.path)
        return senders, handled, reads, provided_any

    def param_reads(self, fi: FuncInfo, pname: str, roles: tuple,
                    _stack: Optional[frozenset] = None) -> dict:
        """{type|'*': {field: (line, hard)}} for a message param,
        including reads by callees it is forwarded to."""
        key = (fi.qual, pname, roles)
        if key in self._reads_memo:
            return self._reads_memo[key]
        stack = _stack or frozenset()
        if key in stack:
            return {}
        stack = stack | {key}
        s = self.scan(fi, roles)
        out: Dict[str, Dict[str, Tuple[int, bool]]] = {}
        for t, fields in s.reads.get(pname, {}).items():
            out.setdefault(t, {}).update(fields)
        for ev in s.calls:
            for pos, d in enumerate(ev.args):
                if ev.arg_names.get(pos) != pname:
                    continue
                got = self._callee_param(ev, pos)
                if got is None:
                    continue
                cfi, cpname = got
                # derive roles when the callee also gets the alias
                croles: tuple = ()
                for mpos, tpos in ev.alias_pairs:
                    if mpos == pos:
                        got_t = self._callee_param(ev, tpos)
                        if got_t is not None:
                            croles = ((got_t[1], cpname),)
                child = self.param_reads(cfi, cpname, croles, stack)
                ts = ev.constraints.get(pos)
                for ct, fields in child.items():
                    if ct == "*" and ts:
                        for t in ts:
                            out.setdefault(t, {}).update(fields)
                    else:
                        out.setdefault(ct, {}).update(fields)
        self._reads_memo[key] = out
        return out


def check(idx: ProjectIndex, cxx_idx=None):
    """Returns (findings, inventory rows).

    When `cxx_idx` (a :class:`.cxx.CxxIndex`) is given, the C++
    sources join the protocol graph: native ``"{\\"type\\": ...}"``
    constructions count as senders (so handler arms for messages the
    C++ client produces are no longer orphans needing baseline
    entries), native dispatch arms count as handlers, and types the
    C++ side sends are exempt from ``proto-missing-field`` (their
    field sets are not statically visible from here)."""
    from ..raylint import Finding

    senders, handled, reads, provided_any = _Analyzer(idx).run()
    findings: List[Finding] = []
    cxx_sent = dict(cxx_idx.sent) if cxx_idx is not None else {}
    cxx_handled = dict(cxx_idx.dispatch) if cxx_idx is not None else {}

    for t in sorted(senders):
        if t in handled or t in cxx_handled:
            continue
        lit = senders[t][0]
        findings.append(Finding(
            lit.path, lit.line, "proto-orphan-sent",
            f'message type "{t}" is sent here but no handler in the '
            f'tree dispatches on it — the receiver will hit its '
            f'unknown-type path (or hang a caller awaiting a typed '
            f'reply)'))
    for t in sorted(handled):
        if t in senders or t in cxx_sent:
            continue
        path, line = handled[t][0]
        findings.append(Finding(
            path, line, "proto-orphan-handled",
            f'handler dispatches on message type "{t}" but no send '
            f'site in the tree produces it — dead protocol arm, or '
            f'a sender outside this tree (baseline it with the '
            f'sender\'s location as the reason)'))
    for t in sorted(set(senders) & set(handled)):
        if t in cxx_sent:
            continue
        provided: Set[str] = set()
        for lit in senders[t]:
            provided |= lit.fields
        for fld, (path, line, hard) in sorted(
                reads.get(t, {}).items()):
            if not hard or fld in provided or fld in provided_any:
                continue
            findings.append(Finding(
                path, line, "proto-missing-field",
                f'handler for "{t}" hard-reads msg["{fld}"] but no '
                f'sender of "{t}" provides it — KeyError (or a dead '
                f'branch) the first time this path runs'))

    inventory: List[dict] = []
    for t in sorted(set(senders) | set(handled)
                    | set(cxx_sent) | set(cxx_handled)):
        provided = set()
        for lit in senders.get(t, []):
            provided |= lit.fields
        row = {
            "type": t,
            "senders": [f"{lit.path}:{lit.line}"
                        for lit in senders.get(t, [])],
            "handlers": [f"{p}:{ln}"
                         for p, ln in handled.get(t, [])],
            "fields": sorted(provided - {"type"}),
            "reads": sorted(reads.get(t, {})),
        }
        if t in cxx_sent:
            p, ln = cxx_sent[t]
            row["senders"].append(f"{p}:{ln} (C++)")
        if t in cxx_handled:
            p, ln = cxx_handled[t]
            row["handlers"].append(f"{p}:{ln} (C++)")
        if t in NATIVE_PLANE:
            row["native"] = NATIVE_PLANE[t]
            site = cxx_handled.get(t) or cxx_sent.get(t)
            if site is not None:
                row["native_site"] = f"{site[0]}:{site[1]}"
        inventory.append(row)
    return findings, inventory
