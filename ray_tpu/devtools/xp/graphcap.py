"""Static task-graph extraction + capture-soundness checks.

ROADMAP item 3 (compiled task graphs / dispatch-plane replay) needs a
trustworthy answer to "is this pipeline safe to capture once and replay
as pre-encoded frames?". This pass extracts the graph a capture entry
point would record — **nodes** are resolved ``.remote()`` sites and dag
``.bind()`` sites (task, actor creation, actor method, deployment),
**edges** are function-local ObjectRef dataflow between them (a name
bound from one site consumed as an argument of a later site, including
through tuple unpacks, containers and comprehensions) — and verifies
the structural soundness replay depends on:

- **acyclicity** — a site that consumes its own output across loop
  iterations is a feedback edge; the unrolled shape depends on the
  runtime trip count (``xp-graph-shape-drift``).
- **edge arity** — a ``num_returns=0`` producer returns ``None``, so an
  "edge" out of it carries no ref: the consumer would be encoded with a
  dependency that never materializes (``xp-graph-shape-drift``).
- **annotation feasibility** — a node whose resource annotation can
  never be scheduled as captured: negative demands, or ``num_gpus > 0``
  (the TPU runtime rejects it at submit time) — the captured frame
  would fail on every replay (``xp-graph-shape-drift``).
- **runtime-value control flow** — an ``if``/``while``/``for`` whose
  test or bound derives from ``get()`` of a captured ref, guarding
  further submissions: the replayed frame bakes in the captured branch
  direction (``xp-graph-shape-drift``).
- **ref escapes** — a captured ref stored into ``self``/a global: on
  replay the stash aliases the capture iteration's channel, so later
  reads see stale objects (``xp-graph-ref-escape``).
- **same-actor submission order across branches** — two branches that
  submit to the same actors in opposite orders; captured execution
  fixes ONE order per actor, so replaying the other branch reorders
  cross-actor effects (``xp-graph-actor-order``).

Entry points (``find_entries``): functions decorated
``@ray_tpu.graphable`` ("graphable"), functions that call
``compile_dag``/``.experimental_compile()`` ("compile"), and functions
that build a dag/serve graph with ``.bind(...)`` ("bind" — discovered
by running the capture and keeping functions that yield bind nodes, so
``socket.bind()`` look-alikes stay out). The captured region is the
entry's reachable set over the resolved call graph, pruned at the
runtime plane: replay replaces the dispatch machinery, so the analysis
walks DRIVER code and stops where `core/`, `dag/`, the serve runtime
and the public API begin. Edges stay function-local (a ref passed into
a helper is consumption, not an edge) — conservative by construction,
like the rest of the dataflow tier.

Per-entry graphs are emitted as artifacts (``raylint --graph-out``) for
the dispatch-plane replay PR and for the static↔dynamic verifier
(tests/test_graph_capture.py), which reconstructs the dynamic graph
from task lifecycle stamps and asserts the capture matches reality.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .dataflow import (CallGraph, ClassInfo, FuncInfo, RemoteResolver,
                       _iter_calls, _stmt_bodies, remote_decoration,
                       resolve_value)
from .index import ProjectIndex
from .reflife import _is_get, _is_put

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)

# The capture boundary: replay replaces the dispatch plane, so graph
# extraction walks driver code and stops where the runtime begins.
# Entries themselves are never pruned — only traversal beyond them.
_RUNTIME_PLANE = (
    "ray_tpu.core", "ray_tpu.util", "ray_tpu.observability",
    "ray_tpu.devtools", "ray_tpu.dag", "ray_tpu._private",
    "ray_tpu._native", "ray_tpu.state", "ray_tpu.client",
    "ray_tpu.dashboard", "ray_tpu.autoscaler", "ray_tpu.node",
    "ray_tpu.serve.api", "ray_tpu.serve.controller",
    "ray_tpu.serve.handle", "ray_tpu.serve.router",
    "ray_tpu.serve.replica", "ray_tpu.serve.proxy",
    "ray_tpu.serve.node_proxy", "ray_tpu.serve.grpc_proxy",
    "ray_tpu.serve.deployment", "ray_tpu.serve.batching",
)


def _runtime_module(modname: str) -> bool:
    if modname == "ray_tpu":
        return True
    return any(modname == p or modname.startswith(p + ".")
               for p in _RUNTIME_PLANE)


def capture_reach(graph: CallGraph, idx: ProjectIndex,
                  root: str) -> Dict[str, List[str]]:
    """`CallGraph.reachable`, pruned at the runtime plane.

    Traversal stops at callees living in dispatch-machinery modules
    (replay replaces those; judging them would flag the runtime for
    doing its job) — except the entry's own module, which is driver
    code by declaration even when it happens to sit under a runtime
    package (e.g. the ``_private.perf`` benchmark driver).
    """
    root_fi = idx.functions.get(root)
    home = root_fi.module.modname if root_fi is not None else None
    out: Dict[str, List[str]] = {}
    queue: List[Tuple[str, List[str]]] = [(root, [root])]
    while queue:
        q, chain = queue.pop(0)
        if q in out:
            continue
        out[q] = chain
        for callee in sorted(graph.edges.get(q, ())):
            if callee in out:
                continue
            fi = idx.functions.get(callee)
            if (fi is not None and fi.module.modname != home
                    and _runtime_module(fi.module.modname)):
                continue
            queue.append((callee, chain + [callee]))
    return out


# ---------------------------------------------------------------------
# Entry discovery
# ---------------------------------------------------------------------


@dataclass
class GraphEntry:
    fi: FuncInfo
    line: int
    kind: str        # "graphable" | "compile" | "bind"


def _dec_name(dec: ast.AST) -> Optional[str]:
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def is_graphable_def(node: ast.AST) -> bool:
    return any(_dec_name(d) == "graphable"
               for d in getattr(node, "decorator_list", []))


def _compile_line(fi: FuncInfo,
                  resolver: RemoteResolver) -> Optional[int]:
    """Line of the first compile_dag()/.experimental_compile() call."""
    for call in resolver.calls_in(fi.node):
        f = call.func
        name = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None)
        if name in ("compile_dag", "experimental_compile"):
            return call.lineno
    return None


def _has_bind_call(fi: FuncInfo, resolver: RemoteResolver) -> bool:
    return any(isinstance(c.func, ast.Attribute) and c.func.attr == "bind"
               for c in resolver.calls_in(fi.node))


def find_entries(idx: ProjectIndex,
                 resolver: RemoteResolver) -> List[GraphEntry]:
    """Graph-capture entry points, "bind" candidates included — a bind
    candidate is confirmed only if its capture yields a bind node."""
    entries: List[GraphEntry] = []
    seen: Set[str] = set()

    def add(fi: FuncInfo, line: int, kind: str) -> None:
        if fi.qual in seen:
            return
        seen.add(fi.qual)
        entries.append(GraphEntry(fi, line, kind))

    for fi in idx.all_functions():
        for dec in getattr(fi.node, "decorator_list", []):
            if _dec_name(dec) == "graphable":
                add(fi, dec.lineno, "graphable")
        if fi.qual in seen:
            continue
        cl = _compile_line(fi, resolver)
        if cl is not None:
            add(fi, cl, "compile")
        elif _has_bind_call(fi, resolver):
            add(fi, fi.node.lineno, "bind")
    return entries


# ---------------------------------------------------------------------
# Graph model
# ---------------------------------------------------------------------


@dataclass
class GraphNode:
    id: str
    kind: str        # task|actor_create|actor_method|bind_*|deploy
    label: str
    path: str
    line: int
    conditional: bool
    void: bool = False                 # num_returns=0 producer
    options: Dict[str, object] = field(default_factory=dict)


@dataclass
class Graph:
    entry: GraphEntry
    nodes: List[GraphNode] = field(default_factory=list)
    edges: List[Tuple[str, str]] = field(default_factory=list)

    def to_dict(self) -> dict:
        e = self.entry
        return {
            "entry": e.fi.qual,
            "kind": e.kind,
            "path": e.fi.path,
            "line": e.line,
            "nodes": [{
                "id": n.id, "kind": n.kind, "label": n.label,
                "path": n.path, "line": n.line,
                "conditional": n.conditional,
                **({"void": True} if n.void else {}),
                **({"options": n.options} if n.options else {}),
            } for n in self.nodes],
            "edges": [list(p) for p in self.edges],
        }


def _literal_options(options: Dict[str, ast.expr]) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in options.items():
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = "<dynamic>"
    return out


# ---------------------------------------------------------------------
# Per-function capture
# ---------------------------------------------------------------------


class _FnCapture:
    """Statement-ordered capture of one reached function: creates
    nodes for submission/bind sites, wires ref-dataflow edges, and
    raises the structural findings that are local to this body."""

    def __init__(self, g: Graph, fi: FuncInfo, entry: GraphEntry,
                 resolver: RemoteResolver, idx: ProjectIndex,
                 is_entry_fn: bool):
        self.g = g
        self.fi = fi
        self.entry = entry
        self.resolver = resolver
        self.idx = idx
        self.is_entry_fn = is_entry_fn
        self.env = resolver.seed_env(fi)
        self.findings: List[tuple] = []   # (line, rule, message)
        # name -> producing node ids (refs or dag nodes the name holds)
        self.made: Dict[str, Set[str]] = {}
        # name -> node ids whose ref was get()-materialized into it
        self.derived: Dict[str, Set[str]] = {}
        # names holding bare put() refs (no producing node)
        self.put_names: Set[str] = set()
        # name -> deployment label, for X = deployment(...) locals
        self.deployments: Dict[str, str] = {}
        self.branch_depth = 0
        self.loop_depth = 0
        self._node_memo: Dict[int, Optional[Set[str]]] = {}

    # -- node/edge creation -------------------------------------------

    def _new_node(self, kind: str, label: str, line: int,
                  options: Optional[Dict[str, ast.expr]] = None,
                  void: bool = False) -> str:
        nid = f"n{len(self.g.nodes)}"
        conditional = (not self.is_entry_fn or self.branch_depth > 0
                       or self.loop_depth > 0)
        self.g.nodes.append(GraphNode(
            nid, kind, label, self.fi.path, line, conditional, void,
            _literal_options(options or {})))
        return nid

    def _edge(self, src: str, dst: str) -> None:
        if (src, dst) not in self.g.edges:
            self.g.edges.append((src, dst))
        node = next(n for n in self.g.nodes if n.id == src)
        if node.void:
            self.findings.append((
                node.line, "xp-graph-shape-drift",
                f"edge out of a num_returns=0 producer "
                f"({node.label}) in the captured graph of "
                f"{self.entry.fi.name}() — the submission returns "
                f"None, so the consumer would be encoded with a "
                f"dependency that never materializes; drop "
                f"num_returns=0 or stop passing the result on"))

    def _check_options(self, nid: str, label: str, line: int,
                       options: Dict[str, ast.expr]) -> None:
        for key in ("num_cpus", "num_tpus", "memory"):
            v = options.get(key)
            if (isinstance(v, ast.Constant)
                    and isinstance(v.value, (int, float))
                    and v.value < 0):
                self.findings.append((
                    line, "xp-graph-shape-drift",
                    f"node {label} in the captured graph of "
                    f"{self.entry.fi.name}() demands {key}={v.value} "
                    f"— a negative demand can never be scheduled, so "
                    f"every replay of the captured frame fails"))
        g = options.get("num_gpus")
        if (isinstance(g, ast.Constant)
                and isinstance(g.value, (int, float)) and g.value > 0):
            self.findings.append((
                line, "xp-graph-shape-drift",
                f"node {label} in the captured graph of "
                f"{self.entry.fi.name}() is annotated "
                f"num_gpus={g.value} — the TPU runtime rejects "
                f"num_gpus at submit time (use num_tpus), so the "
                f"captured frame cannot be scheduled as annotated"))

    # -- expression evaluation ----------------------------------------

    def eval_expr(self, expr: ast.AST) -> Set[str]:
        """Node ids the expression's value carries (refs/dag nodes)."""
        if isinstance(expr, ast.Await):
            return self.eval_expr(expr.value)
        if isinstance(expr, ast.Name):
            return set(self.made.get(expr.id, ()))
        if isinstance(expr, ast.Starred):
            return self.eval_expr(expr.value)
        if isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
            out: Set[str] = set()
            for e in expr.elts:
                out |= self.eval_expr(e)
            return out
        if isinstance(expr, ast.IfExp):
            return self.eval_expr(expr.body) | self.eval_expr(expr.orelse)
        if isinstance(expr, ast.Subscript):
            # refs[i] — element of a list a site produced
            return self.eval_expr(expr.value)
        if isinstance(expr, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            self.resolver.bind_comps(self.env, expr, self.fi)
            return self.eval_expr(expr.elt)
        if isinstance(expr, ast.DictComp):
            self.resolver.bind_comps(self.env, expr, self.fi)
            return self.eval_expr(expr.value)
        if isinstance(expr, ast.Call):
            return self._eval_call(expr)
        return set()

    def _eval_call(self, call: ast.Call) -> Set[str]:
        memo = self._node_memo.get(id(call))
        if memo is not None:
            return memo
        # arguments first (post-order: inner sites become nodes and
        # feed edges into this one)
        inputs: Set[str] = set()
        for a in call.args:
            inputs |= self.eval_expr(a)
        for kw in call.keywords:
            inputs |= self.eval_expr(kw.value)

        nids = self._classify(call, inputs)
        if nids is None:
            # not a graph site: a helper swallowing a ref is
            # consumption, not an edge
            nids = set()
        self._node_memo[id(call)] = nids
        return nids

    def _classify(self, call: ast.Call,
                  inputs: Set[str]) -> Optional[Set[str]]:
        f = call.func
        # X.remote(...)
        if isinstance(f, ast.Attribute) and f.attr == "remote":
            site = self.resolver.site(call, self.fi, self.env)
            if site is None:
                return None
            if site.kind == "actor_method":
                base = (site.target.name if isinstance(
                    site.target, ClassInfo) else "<actor>")
                label = f"{base}.{site.method_name}"
            elif site.kind == "actor_create":
                label = site.target.name if site.target else "<actor>"
            else:
                label = site.target.name if site.target else "<task>"
            nr = site.options.get("num_returns")
            void = isinstance(nr, ast.Constant) and nr.value == 0
            nid = self._new_node(site.kind, label, call.lineno,
                                 site.options, void)
            self._check_options(nid, label, call.lineno, site.options)
            for src in inputs:
                self._edge(src, nid)
            return {nid}
        # X.bind(...) — dag node or serve deployment bind
        if isinstance(f, ast.Attribute) and f.attr == "bind":
            return self._bind_node(call, f.value, inputs)
        # InputNode()/MultiOutputNode(...) are graph plumbing, not
        # tasks: pass inputs through so bind chains stay connected
        name = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None)
        if name in ("InputNode", "MultiOutputNode"):
            return inputs
        return None

    def _bind_node(self, call: ast.Call, recv: ast.AST,
                   inputs: Set[str]) -> Optional[Set[str]]:
        made: Optional[Tuple[str, str]] = None     # (kind, label)
        # deployment local: dep = deployment(Model); dep.bind(...)
        if isinstance(recv, ast.Name) and recv.id in self.deployments:
            made = ("deploy", f"deploy:{self.deployments[recv.id]}")
        if made is None and isinstance(recv, ast.Attribute):
            # method bind on a live handle / class node:
            # d.double.bind(x) — receiver typed by provenance
            actor = self.resolver._handle_of(recv.value, self.fi,
                                             self.env)
            if actor is not None:
                made = ("bind_method", f"{actor.name}.{recv.attr}")
        if made is None:
            r = resolve_value(recv, self.fi, self.idx)
            if r is not None and not isinstance(r, FuncInfo) \
                    and not isinstance(r, ClassInfo):
                r = None
            if r is not None:
                dep = self._deployment_name(r)
                if dep is not None:
                    made = ("deploy", f"deploy:{dep}")
                elif remote_decoration(r.node) is not None:
                    kind = ("bind_class" if isinstance(r, ClassInfo)
                            else "bind_function")
                    made = (kind, r.name)
        # deployment(...).bind(...) inline
        if made is None and isinstance(recv, ast.Call):
            dep = self._deployment_call_name(recv)
            if dep is not None:
                made = ("deploy", f"deploy:{dep}")
        if made is None:
            return None              # socket.bind() and friends
        kind, label = made
        nid = self._new_node(kind, label, call.lineno)
        for src in inputs:
            self._edge(src, nid)
        return {nid}

    def _deployment_name(self, r) -> Optional[str]:
        """Deployment name when `r` is an @deployment-decorated def."""
        for dec in getattr(r.node, "decorator_list", []):
            if _dec_name(dec) != "deployment":
                continue
            if isinstance(dec, ast.Call):
                for kw in dec.keywords:
                    if kw.arg == "name" and isinstance(
                            kw.value, ast.Constant):
                        return str(kw.value.value)
            return r.name
        return None

    def _deployment_call_name(self, call: ast.AST) -> Optional[str]:
        """Name for ``deployment(Model, name=...)`` / with ``.options``
        hops, or None when `call` is not a deployment factory call."""
        for _ in range(4):
            if not isinstance(call, ast.Call):
                return None
            f = call.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else None)
            if name == "options" and isinstance(f, ast.Attribute):
                for kw in call.keywords:
                    if kw.arg == "name" and isinstance(
                            kw.value, ast.Constant):
                        return str(kw.value.value)
                call = f.value
                continue
            if name != "deployment":
                return None
            for kw in call.keywords:
                if kw.arg == "name" and isinstance(
                        kw.value, ast.Constant):
                    return str(kw.value.value)
            if call.args:
                r = resolve_value(call.args[0], self.fi, self.idx)
                got = getattr(r, "name", None) if r is not None else None
                if got:
                    return got
                if isinstance(call.args[0], ast.Name):
                    return call.args[0].id
            return None
        return None

    # -- statement walk -----------------------------------------------

    def run(self) -> List[tuple]:
        self._walk(list(getattr(self.fi.node, "body", [])),
                   branch=False, loop=False)
        return self.findings

    def _walk(self, stmts: List[ast.stmt], branch: bool,
              loop: bool) -> None:
        if branch:
            self.branch_depth += 1
        if loop:
            self.loop_depth += 1
        try:
            for stmt in stmts:
                if isinstance(stmt, _SKIP_NODES):
                    continue
                self._stmt(stmt)
                self._recurse(stmt)
        finally:
            if branch:
                self.branch_depth -= 1
            if loop:
                self.loop_depth -= 1

    def _recurse(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.If):
            self._check_drift(stmt.test, stmt)
            self._check_actor_order(stmt)
            self._walk(stmt.body, branch=True, loop=False)
            self._walk(stmt.orelse, branch=True, loop=False)
            return
        if isinstance(stmt, ast.While):
            self._check_drift(stmt.test, stmt)
            self._walk(stmt.body, branch=True, loop=True)
            self._walk(stmt.orelse, branch=True, loop=False)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_drift(stmt.iter, stmt)
            self._walk(stmt.body, branch=False, loop=True)
            self._walk(stmt.orelse, branch=True, loop=False)
            return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, branch=False, loop=False)
            for h in stmt.handlers:
                self._walk(h.body, branch=True, loop=False)
            self._walk(stmt.orelse, branch=False, loop=False)
            self._walk(stmt.finalbody, branch=False, loop=False)
            return
        for body in _stmt_bodies(stmt):     # With etc.
            self._walk(body, branch=False, loop=False)

    def _stmt(self, stmt: ast.stmt) -> None:
        # comprehension provenance for every expression child
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, (ast.stmt, ast.excepthandler)):
                self.resolver.bind_comps(self.env, child, self.fi)
        if isinstance(stmt, ast.Assign):
            self._assign(stmt)
            return
        if isinstance(stmt, ast.AnnAssign):
            self.resolver.bind(self.env, stmt, self.fi)
            if stmt.value is not None and isinstance(
                    stmt.target, ast.Name):
                self._bind_value(stmt.target.id, stmt.value)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.resolver.bind_for(self.env, stmt, self.fi)
            self.eval_expr(stmt.iter)
            # for r in refs: — loop var carries the producer ids
            it = stmt.iter
            if (isinstance(it, ast.Name) and it.id in self.made
                    and isinstance(stmt.target, ast.Name)):
                self.made[stmt.target.id] = set(self.made[it.id])
            return
        if isinstance(stmt, ast.Expr):
            self.resolver.bind_append(self.env, stmt.value, self.fi)
            self._expr_stmt(stmt.value)
            return
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if getattr(stmt, "value", None) is not None:
                self.eval_expr(stmt.value)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.eval_expr(stmt.test)
            return
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, (ast.stmt, ast.excepthandler)):
                self.eval_expr(child)

    def _expr_stmt(self, v: ast.AST) -> None:
        # xs.append(site(...)) — container membership; self-container
        # appends of made refs are escapes
        if (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr in ("append", "add", "insert")
                and v.args):
            ids = self.eval_expr(v.args[-1])
            recv = v.func.value
            if isinstance(recv, ast.Name):
                if ids:
                    self.made.setdefault(recv.id, set()).update(ids)
                return
            if self._is_self_attr(recv) and self._ref_ids(ids):
                self._escape(v.lineno, f"self.{recv.attr}",
                             "appended to")
            return
        self.eval_expr(v)

    def _assign(self, stmt: ast.Assign) -> None:
        self.resolver.bind(self.env, stmt, self.fi)
        v = stmt.value
        ids = self.eval_expr(v)
        is_put = self._put_value(v)
        get_ids = self._get_source_ids(v)
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                self._bind_name(tgt.id, ids, is_put, get_ids)
            elif isinstance(tgt, ast.Tuple):
                for e in tgt.elts:
                    if isinstance(e, ast.Name):
                        self._bind_name(e.id, ids, is_put, get_ids)
            elif self._is_self_attr(tgt):
                if self._ref_ids(ids) or is_put:
                    self._escape(stmt.lineno, f"self.{tgt.attr}",
                                 "stored into")

    def _bind_value(self, name: str, v: ast.AST) -> None:
        self._bind_name(name, self.eval_expr(v), self._put_value(v),
                        self._get_source_ids(v))

    def _bind_name(self, name: str, ids: Set[str], is_put: bool,
                   get_ids: Set[str]) -> None:
        self.made.pop(name, None)
        self.derived.pop(name, None)
        self.put_names.discard(name)
        if ids:
            self.made[name] = set(ids)
        if is_put:
            self.put_names.add(name)
        if get_ids:
            self.derived[name] = set(get_ids)

    def _put_value(self, v: ast.AST) -> bool:
        if isinstance(v, ast.Name) and v.id in self.put_names:
            return True
        if isinstance(v, ast.Call) and _is_put(v, self.fi, self.idx):
            return True
        if isinstance(v, (ast.ListComp, ast.SetComp)):
            return self._put_value(v.elt)
        if isinstance(v, (ast.List, ast.Tuple, ast.Set)):
            return bool(v.elts) and any(
                self._put_value(e) for e in v.elts)
        return False

    def _get_source_ids(self, v: ast.AST) -> Set[str]:
        """Producer node ids when `v` is get(<made name>)."""
        if isinstance(v, ast.Call) and _is_get(v, self.fi, self.idx) \
                and v.args:
            return self.eval_expr(v.args[0])
        if isinstance(v, ast.Subscript):
            return self._get_source_ids(v.value)
        return set()

    @staticmethod
    def _is_self_attr(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    # -- findings -----------------------------------------------------

    def _ref_ids(self, ids: Set[str]) -> Set[str]:
        """Subset of `ids` whose nodes produce ObjectRefs."""
        by_id = {n.id: n for n in self.g.nodes}
        return {i for i in ids
                if by_id[i].kind in ("task", "actor_method")
                and not by_id[i].void}

    def _escape(self, line: int, where: str, how: str) -> None:
        self.findings.append((
            line, "xp-graph-ref-escape",
            f"captured ref {how} {where} inside the graph of "
            f"{self.entry.fi.name}() — on replay the stash still "
            f"points at the capture iteration's channel, so reads "
            f"after replay see stale objects; thread the ref through "
            f"the graph instead, or keep the stash outside the "
            f"captured region"))

    def _check_drift(self, test: ast.AST, stmt: ast.stmt) -> None:
        names = {n.id for n in ast.walk(test)
                 if isinstance(n, ast.Name)
                 and isinstance(n.ctx, ast.Load)}
        hot = sorted(names & set(self.derived))
        if not hot:
            return
        if not self._guards_submission(stmt):
            return
        word = ("loop bound" if isinstance(
            stmt, (ast.While, ast.For, ast.AsyncFor)) else "branch")
        self.findings.append((
            stmt.lineno, "xp-graph-shape-drift",
            f"{word} on `{hot[0]}` (get()-materialized from a "
            f"captured submission) guards further submissions in the "
            f"graph of {self.entry.fi.name}() — the replayed frame "
            f"bakes in the captured direction, so an iteration whose "
            f"runtime value differs diverges from the capture; make "
            f"the shape static or leave this pipeline uncaptured"))

    def _guards_submission(self, stmt: ast.stmt) -> bool:
        for body in _stmt_bodies(stmt):
            for s in body:
                for call in _iter_calls(s):
                    f = call.func
                    if isinstance(f, ast.Attribute) \
                            and f.attr in ("remote", "bind"):
                        return True
        return False

    def _check_actor_order(self, stmt: ast.If) -> None:
        if not stmt.orelse:
            return
        a = self._branch_order(stmt.body)
        b = self._branch_order(stmt.orelse)
        common = [k for k in a if k in b]
        for i, x in enumerate(common):
            for y in common[i + 1:]:
                if (a[x] < a[y]) != (b[x] < b[y]):
                    self.findings.append((
                        stmt.lineno, "xp-graph-actor-order",
                        f"the two branches submit to the same actors "
                        f"({x}, {y}) in opposite orders inside the "
                        f"captured graph of {self.entry.fi.name}() — "
                        f"capture fixes ONE submission order per "
                        f"actor, so replaying the other branch "
                        f"reorders cross-actor effects; hoist the "
                        f"submissions out of the branch or make the "
                        f"order consistent"))
                    return

    def _branch_order(self, stmts: List[ast.stmt]) -> Dict[str, int]:
        """receiver-expr -> first submission index, in order."""
        order: Dict[str, int] = {}
        i = 0
        for s in stmts:
            if isinstance(s, _SKIP_NODES):
                continue
            for call in _iter_calls(s):
                f = call.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr == "remote"):
                    continue
                site = self.resolver.site(call, self.fi, self.env)
                if site is None or site.kind != "actor_method":
                    continue
                recv = ast.unparse(f.value.value) if isinstance(
                    f.value, ast.Attribute) else ast.unparse(f.value)
                if recv not in order:
                    order[recv] = i
                i += 1
        return order


# ---------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------


def capture_entry(entry: GraphEntry, idx: ProjectIndex,
                  graph: CallGraph,
                  resolver: RemoteResolver) -> Tuple[Graph, List[tuple]]:
    """(captured graph, raw findings) for one entry point. Findings
    are (path, line, rule, message) tuples."""
    g = Graph(entry)
    raw: List[tuple] = []
    reach = capture_reach(graph, idx, entry.fi.qual)
    ordered = sorted(reach.items(), key=lambda kv: (len(kv[1]), kv[0]))
    for qual, _chain in ordered:
        fi = idx.functions.get(qual)
        if fi is None:
            continue
        cap = _FnCapture(g, fi, entry, resolver, idx,
                         is_entry_fn=(qual == entry.fi.qual))
        # deployment factory locals need a pre-pass: dep = deployment(X)
        _seed_deployments(cap)
        for line, rule, msg in cap.run():
            raw.append((fi.path, line, rule, msg))
    raw.extend(_cycle_findings(g))
    return g, raw


def _seed_deployments(cap: _FnCapture) -> None:
    for n in ast.walk(cap.fi.node):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)):
            continue
        dep = cap._deployment_call_name(n.value)
        if dep is not None:
            cap.deployments[n.targets[0].id] = dep


def _cycle_findings(g: Graph) -> List[tuple]:
    """DFS cycle check over the captured edges."""
    adj: Dict[str, List[str]] = {}
    for s, d in g.edges:
        adj.setdefault(s, []).append(d)
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n.id: WHITE for n in g.nodes}
    labels = {n.id: n for n in g.nodes}
    out: List[tuple] = []

    def dfs(u: str) -> Optional[str]:
        color[u] = GREY
        for v in adj.get(u, ()):
            if color[v] == GREY:
                return v
            if color[v] == WHITE:
                got = dfs(v)
                if got is not None:
                    return got
        color[u] = BLACK
        return None

    for n in g.nodes:
        if color[n.id] != WHITE:
            continue
        hit = dfs(n.id)
        if hit is not None:
            node = labels[hit]
            out.append((
                node.path, node.line, "xp-graph-shape-drift",
                f"feedback edge: {node.label} consumes its own "
                f"output across loop iterations in the captured "
                f"graph of {g.entry.fi.name}() — the unrolled shape "
                f"depends on the runtime trip count, so a fixed "
                f"captured frame cannot represent it"))
            return out
    return out


def check(idx: ProjectIndex, graph: Optional[CallGraph] = None,
          resolver: Optional[RemoteResolver] = None,
          only: Optional[Set[str]] = None,
          graphs: Optional[List[dict]] = None) -> List:
    """Findings for all entries; when `graphs` is a list it is filled
    in place with per-entry graph artifacts (bind candidates that
    yield no bind node are dropped)."""
    from ..raylint import Finding

    resolver = resolver or RemoteResolver(idx)
    graph = graph or CallGraph(idx)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for entry in find_entries(idx, resolver):
        g, raw = capture_entry(entry, idx, graph, resolver)
        if entry.kind == "bind" and not any(
                n.kind.startswith("bind") or n.kind == "deploy"
                for n in g.nodes):
            continue                      # socket.bind look-alike
        if graphs is not None:
            graphs.append(g.to_dict())
        for path, line, rule, msg in raw:
            if only is not None and path not in only:
                continue
            key = (path, line, rule)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(path, line, rule, msg))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
