"""Cross-file lock-order inversion detection.

The per-file ``lock-order-inversion`` rule sees both orders only when
each is nested directly inside one module. This pass closes the gap
ROADMAP item 5 deferred: it normalizes lock identities project-wide
(``self._lock`` in class C of module m -> ``m.C._lock``; a module
global -> ``m._LOCK``), records which locks every function acquires,
computes the transitive closure of acquisition over the call graph,
and then adds an order edge ``H -> L`` for every call site that runs
with ``H`` held into a callee whose closure acquires ``L``. A pair
with edges in both directions — where at least one side needed the
call graph to see — is a deadlock the per-file rule cannot catch.

Identity is class-level, not instance-level: two *distinct* instances
of one class can legally nest ``a._lock`` inside ``b._lock`` in both
orders without deadlocking, but code doing that is already beyond
what a static pass can bless, and the runtime ``locktrace`` checker
(which tracks real lock objects) adjudicates those. Locks that stay
function-local (``lk = threading.Lock()`` in a body) get a
function-scoped key, so they self-order but never create cross-file
edges.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..raylint import _expr_key, _lockish, _terminal_name
from .index import FuncInfo, ProjectIndex, _child_stmts

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)


@dataclass
class CallSite:
    callee: Optional[str]      # resolved callee qual, or None
    held: Tuple[str, ...]
    line: int
    # unresolved attribute calls keep their shape so the cross-language
    # pass can spot `lib.nd_stop(...)`-style calls into the native lib
    attr: Optional[str] = None
    recv: Optional[str] = None


@dataclass
class FnLocks:
    fn: FuncInfo
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)


def _local_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    # assignments are statements — a statement-list walk (not a full
    # AST walk) sees them all, skipping nested defs like before
    stack = [fn]
    while stack:
        node = stack.pop()
        for n in _child_stmts(node):
            if isinstance(n, _SKIP_NODES):
                continue
            stack.append(n)
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                if isinstance(n.target, ast.Name):
                    out.add(n.target.id)
    args = getattr(fn, "args", None)
    if args is not None:
        out.update(p.arg for p in args.posonlyargs + args.args
                   + args.kwonlyargs)
    return out


def _norm_lock(expr: ast.AST, fi: FuncInfo, idx: ProjectIndex,
               local: Set[str]) -> str:
    """Project-wide identity for a lock expression."""
    if isinstance(expr, ast.Attribute):
        v = expr.value
        if (isinstance(v, ast.Name) and v.id in ("self", "cls")
                and fi.cls is not None):
            return f"{fi.cls.qual}.{expr.attr}"
        if isinstance(v, ast.Name):
            mod = idx.modules.get(
                fi.module.imports.get(v.id, ""))
            if mod is not None:
                return f"{mod.modname}.{expr.attr}"
            t = fi.module.attr_types.get(v.id)
            if t is not None:
                return f"{t}.{expr.attr}"
        if (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self" and fi.cls is not None):
            t = idx.attr_type(fi.cls.qual, v.attr)
            if t is not None:
                return f"{t}.{expr.attr}"
    elif isinstance(expr, ast.Name):
        if expr.id not in local:
            return f"{fi.module.modname}.{expr.id}"
    # function-local / unresolvable: scope the key to this function
    return f"{fi.qual}:{_expr_key(expr)}"


def _scan(fi: FuncInfo, idx: ProjectIndex) -> FnLocks:
    out = FnLocks(fi)
    local = _local_names(fi.node)
    held: List[str] = []

    def norm(expr: ast.AST) -> str:
        return _norm_lock(expr, fi, idx, local)

    def record_acquire(key: str, line: int) -> None:
        out.acquires.append((key, line))
        for outer in held:
            if outer != key:
                out.edges.append((outer, key, line))

    def note_calls(node: ast.AST) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, _SKIP_NODES):
                continue
            if isinstance(n, ast.Call):
                callee = idx.resolve_call(n.func, fi)
                attr = recv = None
                if isinstance(n.func, ast.Attribute):
                    attr = n.func.attr
                    recv = _terminal_name(n.func.value)
                out.calls.append(CallSite(
                    callee.qual if callee else None, tuple(held),
                    getattr(n, "lineno", 0), attr, recv))
            stack.extend(ast.iter_child_nodes(n))

    def process(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call):
                c = stmt.value
                if (isinstance(c.func, ast.Attribute)
                        and c.func.attr in ("acquire", "release")
                        and _lockish(c.func.value)):
                    key = norm(c.func.value)
                    if c.func.attr == "acquire":
                        record_acquire(key, c.lineno)
                        held.append(key)
                    elif key in held:
                        for i in range(len(held) - 1, -1, -1):
                            if held[i] == key:
                                del held[i]
                                break
                    continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                taken = []
                for item in stmt.items:
                    note_calls(item.context_expr)
                    if _lockish(item.context_expr):
                        key = norm(item.context_expr)
                        record_acquire(key,
                                       item.context_expr.lineno)
                        held.append(key)
                        taken.append(key)
                process(stmt.body)
                for _ in taken:
                    held.pop()
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                note_calls(stmt.test)
                process(stmt.body)
                process(stmt.orelse)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                note_calls(stmt.iter)
                process(stmt.body)
                process(stmt.orelse)
                continue
            if isinstance(stmt, ast.Try):
                process(stmt.body)
                for h in stmt.handlers:
                    process(h.body)
                process(stmt.orelse)
                process(stmt.finalbody)
                continue
            note_calls(stmt)

    process(list(getattr(fi.node, "body", [])))
    return out


@dataclass
class Witness:
    direct: bool
    fn: str
    path: str
    line: int
    desc: str


def scan_all(idx: ProjectIndex) -> Dict[str, FnLocks]:
    """Per-function lock scans, shared by :func:`check` and
    :func:`check_xlang` so ``run_xp`` only walks the tree once."""
    return {fi.qual: _scan(fi, idx) for fi in idx.all_functions()}


def check(idx: ProjectIndex,
          scans: Optional[Dict[str, FnLocks]] = None) -> List:
    """Run the pass; returns raylint Findings."""
    from ..raylint import Finding

    scans = scans if scans is not None else scan_all(idx)

    # closure[f][lock] = (callee qual | None, line where introduced)
    closure: Dict[str, Dict[str, Tuple[Optional[str], int]]] = {
        q: {k: (None, ln) for k, ln in s.acquires}
        for q, s in scans.items()}
    changed = True
    while changed:
        changed = False
        for q, s in scans.items():
            mine = closure[q]
            for c in s.calls:
                if c.callee is None or c.callee == q:
                    continue
                for lock in closure.get(c.callee, ()):
                    if lock not in mine:
                        mine[lock] = (c.callee, c.line)
                        changed = True

    def chain(fn_qual: str, lock: str, depth: int = 0) -> str:
        if depth > 12:
            return "..."
        via, line = closure[fn_qual][lock]
        if via is None:
            return f"{fn_qual}:{line} acquires `{lock}`"
        return f"{fn_qual}:{line} -> {chain(via, lock, depth + 1)}"

    edges: Dict[Tuple[str, str], List[Witness]] = {}

    def add(a: str, b: str, w: Witness) -> None:
        edges.setdefault((a, b), []).append(w)

    for q, s in scans.items():
        fi = s.fn
        owner = fi.cls.name if fi.cls else None
        for a, b, line in s.edges:
            add(a, b, Witness(
                True, q, fi.path, line,
                f"{q}() acquires `{b}` at {fi.path}:{line} while "
                f"holding `{a}`"))
        for c in s.calls:
            if c.callee is None or not c.held:
                continue
            for lock in closure.get(c.callee, ()):
                for h in c.held:
                    if h != lock:
                        add(h, lock, Witness(
                            False, q, fi.path, c.line,
                            f"{q}() holds `{h}` at {fi.path}:"
                            f"{c.line} while calling "
                            f"{chain(c.callee, lock)} -> takes "
                            f"`{lock}`"))

    # function-local keys never pair across functions; drop them from
    # the global graph entirely (they contain a ':').
    pairs = {(a, b) for (a, b) in edges
             if ":" not in a and ":" not in b}

    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    owner_of: Dict[str, Tuple[str, Optional[str]]] = {
        q: (s.fn.path, s.fn.cls.name if s.fn.cls else None)
        for q, s in scans.items()}
    for (a, b) in sorted(pairs):
        if (b, a) not in pairs or (b, a) in seen:
            continue
        seen.add((a, b))
        fwd, rev = edges[(a, b)], edges[(b, a)]
        # both orders nested directly in one file's class/module group
        # is the per-file rule's finding; only report what NEEDED the
        # call graph.
        same_group_direct = any(
            wf.direct and wr.direct
            and owner_of[wf.fn] == owner_of[wr.fn]
            for wf in fwd for wr in rev)
        cross = [(wf, wr) for wf in fwd for wr in rev
                 if not (wf.direct and wr.direct
                         and owner_of[wf.fn] == owner_of[wr.fn])]
        if same_group_direct and not cross:
            continue
        if not cross:
            continue
        # prefer a witness pair where at least one side crossed a call
        cross.sort(key=lambda p: (p[0].direct + p[1].direct))
        wf, wr = cross[0]
        findings.append(Finding(
            wf.path, wf.line, "xp-lock-order-inversion",
            f"`{a}` -> `{b}`: {wf.desc}; but the opposite order "
            f"exists: {wr.desc} — deadlock when both paths run "
            f"concurrently"))
    return findings


# ---------------------------------------------------------------------------
# Cross-language extension: held sets across the ctypes boundary
# ---------------------------------------------------------------------------

_NATIVE_RECV = ("lib", "_lib", "dll", "_dll", "cdll", "_load", "so",
                "_so")


def _pretty_lock(key: str) -> str:
    # function-scoped keys read better without the qual prefix
    return key.rsplit(":", 1)[-1] if ":" in key else key


def check_xlang(idx: ProjectIndex, cxx_idx,
                scans: Optional[Dict[str, FnLocks]] = None) -> List:
    """Held-set propagation across the FFI boundary, both directions.

    Forward: a Python function that holds a lock (including
    ``HandleGuard`` read/write sections) while calling — directly or
    through the Python call graph — a native export whose body blocks
    unboundedly (``thread.join()``, an untimed condition-variable
    wait) stalls every other thread contending for that lock for as
    long as the native side takes.

    Reverse: a C++ function that acquires ``PyGILState_Ensure`` while
    holding a ``std::mutex`` deadlocks against any Python thread that
    holds the GIL and re-enters the library through an export that
    takes the same mutex.
    """
    from ..raylint import Finding

    findings: List[Finding] = []

    for occs in cxx_idx.functions.values():
        for fn in occs:
            if fn.is_definition and fn.gil_line and fn.locks:
                findings.append(Finding(
                    fn.path, fn.gil_line, "xp-xlang-lock",
                    f"`{fn.name}` ({fn.path}:{fn.line}) calls "
                    f"PyGILState_Ensure while holding "
                    f"`{fn.locks[0]}` — deadlocks against a Python "
                    f"thread that re-enters the library under the "
                    f"GIL and contends for the same mutex"))

    blocking = {}
    for name in cxx_idx.functions:
        cf = cxx_idx.lookup(name)
        if cf is not None and cf.exported and cf.blocking:
            blocking[name] = cf

    if not blocking:
        return findings

    scans = scans if scans is not None else scan_all(idx)

    # native[f][sym] = (via callee qual | None, line) — which blocking
    # exports each Python function can reach, closed over the call
    # graph like the lock closure above.
    native: Dict[str, Dict[str, Tuple[Optional[str], int]]] = {}
    for q, s in scans.items():
        mine: Dict[str, Tuple[Optional[str], int]] = {}
        for c in s.calls:
            if (c.attr in blocking and c.recv is not None
                    and c.recv in _NATIVE_RECV):
                mine.setdefault(c.attr, (None, c.line))
        native[q] = mine
    changed = True
    while changed:
        changed = False
        for q, s in scans.items():
            mine = native[q]
            for c in s.calls:
                if c.callee is None or c.callee == q:
                    continue
                for sym in native.get(c.callee, ()):
                    if sym not in mine:
                        mine[sym] = (c.callee, c.line)
                        changed = True

    def chain(fn_qual: str, sym: str, depth: int = 0) -> str:
        if depth > 12:
            return "..."
        via, line = native[fn_qual][sym]
        if via is None:
            return f"{fn_qual}:{line} calls `{sym}`"
        return f"{fn_qual}:{line} -> {chain(via, sym, depth + 1)}"

    seen: Set[Tuple[str, str, str]] = set()
    for q, s in scans.items():
        fi = s.fn
        for c in s.calls:
            if not c.held:
                continue
            reach: Dict[str, Tuple[Optional[str], int]] = {}
            if (c.attr in blocking and c.recv is not None
                    and c.recv in _NATIVE_RECV):
                reach[c.attr] = (None, c.line)
            elif c.callee is not None and c.callee != q:
                for sym in native.get(c.callee, ()):
                    reach[sym] = (c.callee, c.line)
            for sym in sorted(reach):
                cf = blocking[sym]
                bdesc, bline = cf.blocking[0]
                for h in c.held:
                    key = (q, sym, h)
                    if key in seen:
                        continue
                    seen.add(key)
                    via, _ = reach[sym]
                    route = (f"`{sym}`" if via is None else
                             f"{chain(via, sym)} -> `{sym}`")
                    findings.append(Finding(
                        fi.path, c.line, "xp-xlang-lock",
                        f"{q}() holds `{_pretty_lock(h)}` at "
                        f"{fi.path}:{c.line} while calling {route}, "
                        f"which {bdesc} at {cf.path}:{bline} — the "
                        f"native call can block unboundedly with the "
                        f"lock held"))
    return findings
