"""Cross-file lock-order inversion detection.

The per-file ``lock-order-inversion`` rule sees both orders only when
each is nested directly inside one module. This pass closes the gap
ROADMAP item 5 deferred: it normalizes lock identities project-wide
(``self._lock`` in class C of module m -> ``m.C._lock``; a module
global -> ``m._LOCK``), records which locks every function acquires,
computes the transitive closure of acquisition over the call graph,
and then adds an order edge ``H -> L`` for every call site that runs
with ``H`` held into a callee whose closure acquires ``L``. A pair
with edges in both directions — where at least one side needed the
call graph to see — is a deadlock the per-file rule cannot catch.

Identity is class-level, not instance-level: two *distinct* instances
of one class can legally nest ``a._lock`` inside ``b._lock`` in both
orders without deadlocking, but code doing that is already beyond
what a static pass can bless, and the runtime ``locktrace`` checker
(which tracks real lock objects) adjudicates those. Locks that stay
function-local (``lk = threading.Lock()`` in a body) get a
function-scoped key, so they self-order but never create cross-file
edges.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..raylint import _expr_key, _lockish
from .index import FuncInfo, ProjectIndex, _child_stmts

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)


@dataclass
class CallSite:
    callee: Optional[str]      # resolved callee qual, or None
    held: Tuple[str, ...]
    line: int


@dataclass
class FnLocks:
    fn: FuncInfo
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)


def _local_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    # assignments are statements — a statement-list walk (not a full
    # AST walk) sees them all, skipping nested defs like before
    stack = [fn]
    while stack:
        node = stack.pop()
        for n in _child_stmts(node):
            if isinstance(n, _SKIP_NODES):
                continue
            stack.append(n)
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                if isinstance(n.target, ast.Name):
                    out.add(n.target.id)
    args = getattr(fn, "args", None)
    if args is not None:
        out.update(p.arg for p in args.posonlyargs + args.args
                   + args.kwonlyargs)
    return out


def _norm_lock(expr: ast.AST, fi: FuncInfo, idx: ProjectIndex,
               local: Set[str]) -> str:
    """Project-wide identity for a lock expression."""
    if isinstance(expr, ast.Attribute):
        v = expr.value
        if (isinstance(v, ast.Name) and v.id in ("self", "cls")
                and fi.cls is not None):
            return f"{fi.cls.qual}.{expr.attr}"
        if isinstance(v, ast.Name):
            mod = idx.modules.get(
                fi.module.imports.get(v.id, ""))
            if mod is not None:
                return f"{mod.modname}.{expr.attr}"
            t = fi.module.attr_types.get(v.id)
            if t is not None:
                return f"{t}.{expr.attr}"
        if (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self" and fi.cls is not None):
            t = idx.attr_type(fi.cls.qual, v.attr)
            if t is not None:
                return f"{t}.{expr.attr}"
    elif isinstance(expr, ast.Name):
        if expr.id not in local:
            return f"{fi.module.modname}.{expr.id}"
    # function-local / unresolvable: scope the key to this function
    return f"{fi.qual}:{_expr_key(expr)}"


def _scan(fi: FuncInfo, idx: ProjectIndex) -> FnLocks:
    out = FnLocks(fi)
    local = _local_names(fi.node)
    held: List[str] = []

    def norm(expr: ast.AST) -> str:
        return _norm_lock(expr, fi, idx, local)

    def record_acquire(key: str, line: int) -> None:
        out.acquires.append((key, line))
        for outer in held:
            if outer != key:
                out.edges.append((outer, key, line))

    def note_calls(node: ast.AST) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, _SKIP_NODES):
                continue
            if isinstance(n, ast.Call):
                callee = idx.resolve_call(n.func, fi)
                out.calls.append(CallSite(
                    callee.qual if callee else None, tuple(held),
                    getattr(n, "lineno", 0)))
            stack.extend(ast.iter_child_nodes(n))

    def process(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call):
                c = stmt.value
                if (isinstance(c.func, ast.Attribute)
                        and c.func.attr in ("acquire", "release")
                        and _lockish(c.func.value)):
                    key = norm(c.func.value)
                    if c.func.attr == "acquire":
                        record_acquire(key, c.lineno)
                        held.append(key)
                    elif key in held:
                        for i in range(len(held) - 1, -1, -1):
                            if held[i] == key:
                                del held[i]
                                break
                    continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                taken = []
                for item in stmt.items:
                    note_calls(item.context_expr)
                    if _lockish(item.context_expr):
                        key = norm(item.context_expr)
                        record_acquire(key,
                                       item.context_expr.lineno)
                        held.append(key)
                        taken.append(key)
                process(stmt.body)
                for _ in taken:
                    held.pop()
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                note_calls(stmt.test)
                process(stmt.body)
                process(stmt.orelse)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                note_calls(stmt.iter)
                process(stmt.body)
                process(stmt.orelse)
                continue
            if isinstance(stmt, ast.Try):
                process(stmt.body)
                for h in stmt.handlers:
                    process(h.body)
                process(stmt.orelse)
                process(stmt.finalbody)
                continue
            note_calls(stmt)

    process(list(getattr(fi.node, "body", [])))
    return out


@dataclass
class Witness:
    direct: bool
    fn: str
    path: str
    line: int
    desc: str


def check(idx: ProjectIndex) -> List:
    """Run the pass; returns raylint Findings."""
    from ..raylint import Finding

    scans: Dict[str, FnLocks] = {
        fi.qual: _scan(fi, idx) for fi in idx.all_functions()}

    # closure[f][lock] = (callee qual | None, line where introduced)
    closure: Dict[str, Dict[str, Tuple[Optional[str], int]]] = {
        q: {k: (None, ln) for k, ln in s.acquires}
        for q, s in scans.items()}
    changed = True
    while changed:
        changed = False
        for q, s in scans.items():
            mine = closure[q]
            for c in s.calls:
                if c.callee is None or c.callee == q:
                    continue
                for lock in closure.get(c.callee, ()):
                    if lock not in mine:
                        mine[lock] = (c.callee, c.line)
                        changed = True

    def chain(fn_qual: str, lock: str, depth: int = 0) -> str:
        if depth > 12:
            return "..."
        via, line = closure[fn_qual][lock]
        if via is None:
            return f"{fn_qual}:{line} acquires `{lock}`"
        return f"{fn_qual}:{line} -> {chain(via, lock, depth + 1)}"

    edges: Dict[Tuple[str, str], List[Witness]] = {}

    def add(a: str, b: str, w: Witness) -> None:
        edges.setdefault((a, b), []).append(w)

    for q, s in scans.items():
        fi = s.fn
        owner = fi.cls.name if fi.cls else None
        for a, b, line in s.edges:
            add(a, b, Witness(
                True, q, fi.path, line,
                f"{q}() acquires `{b}` at {fi.path}:{line} while "
                f"holding `{a}`"))
        for c in s.calls:
            if c.callee is None or not c.held:
                continue
            for lock in closure.get(c.callee, ()):
                for h in c.held:
                    if h != lock:
                        add(h, lock, Witness(
                            False, q, fi.path, c.line,
                            f"{q}() holds `{h}` at {fi.path}:"
                            f"{c.line} while calling "
                            f"{chain(c.callee, lock)} -> takes "
                            f"`{lock}`"))

    # function-local keys never pair across functions; drop them from
    # the global graph entirely (they contain a ':').
    pairs = {(a, b) for (a, b) in edges
             if ":" not in a and ":" not in b}

    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    owner_of: Dict[str, Tuple[str, Optional[str]]] = {
        q: (s.fn.path, s.fn.cls.name if s.fn.cls else None)
        for q, s in scans.items()}
    for (a, b) in sorted(pairs):
        if (b, a) not in pairs or (b, a) in seen:
            continue
        seen.add((a, b))
        fwd, rev = edges[(a, b)], edges[(b, a)]
        # both orders nested directly in one file's class/module group
        # is the per-file rule's finding; only report what NEEDED the
        # call graph.
        same_group_direct = any(
            wf.direct and wr.direct
            and owner_of[wf.fn] == owner_of[wr.fn]
            for wf in fwd for wr in rev)
        cross = [(wf, wr) for wf in fwd for wr in rev
                 if not (wf.direct and wr.direct
                         and owner_of[wf.fn] == owner_of[wr.fn])]
        if same_group_direct and not cross:
            continue
        if not cross:
            continue
        # prefer a witness pair where at least one side crossed a call
        cross.sort(key=lambda p: (p[0].direct + p[1].direct))
        wf, wr = cross[0]
        findings.append(Finding(
            wf.path, wf.line, "xp-lock-order-inversion",
            f"`{a}` -> `{b}`: {wf.desc}; but the opposite order "
            f"exists: {wr.desc} — deadlock when both paths run "
            f"concurrently"))
    return findings
