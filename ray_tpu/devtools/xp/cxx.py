"""Heuristic C/C++ extractor for the native-boundary analyses.

``src/*.cc`` is a plain C ABI behind ~100 hand-written ctypes
declarations; nothing checks the two sides against each other until a
stress run (or an outage) does. This module parses just enough C++ —
without a clang dependency, which the image does not carry — to feed
the cross-language passes in :mod:`.ffi` and :mod:`.lockgraph`:

- ``extern "C"`` blocks: exported function signatures (return type,
  parameter types classified by width/signedness/pointer depth);
- struct definitions whose fields are all fixed-width (layout mirrors
  for ``ctypes.Structure`` / ``struct.pack`` checking);
- integer constants (``constexpr``/``const``/``#define``/enums) so a
  Python literal can be pinned to its C++ twin with ``# cxx-const:``;
- ``// cxx-wire: <name> <fmt>`` frame annotations next to the C++
  read/write code, referenced from Python with ``# cxx-wire:``;
- the message-type string dispatch in each handler loop (``mtype ==
  "ping"``) and natively-constructed ``{"type": ...}`` reply
  literals, from which ``protocol.NATIVE_PLANE`` is derived;
- per-function ``std::mutex`` acquisitions plus unbounded blocking
  ops (thread joins, untimed condition-variable waits) and
  ``PyGILState_Ensure`` calls, for cross-boundary lock propagation.

The parser is deliberately shallow: it understands the disciplined
C++ this tree writes (and the fixtures exercise), not the language.
An ``extern "C"`` declaration it cannot parse is an error — surfaced
as ``cxx-parse-error`` under ``raylint --xp`` and a non-zero exit
from ``make -C src lint`` — so drift toward unparseable exports is
loud instead of silently unchecked.

Pure stdlib, importable standalone: ``make -C src lint`` runs this
file directly (``python3 .../cxx.py <dirs-or-files>``) without
importing the ``ray_tpu`` package.
"""

from __future__ import annotations

import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_EXTS = (".c", ".cc", ".cpp", ".cxx", ".h", ".hpp")

# ---------------------------------------------------------------------------
# Type model
# ---------------------------------------------------------------------------

# kind: "void" | "int" | "uint" | "float" | "ptr" | "opaque"
#   "int"/"uint": width in bits; width 8 is the sign-agnostic byte
#   class (char / uint8_t — interchangeable across the FFI).
#   "ptr": pointee is another CType ("opaque" pointee for types we do
#   not model, e.g. a forward-declared struct).


@dataclass(frozen=True)
class CType:
    kind: str
    width: int = 0
    pointee: Optional["CType"] = None
    spelled: str = ""

    def pretty(self) -> str:
        return self.spelled or self.kind


_BASE_TYPES: Dict[str, Tuple[str, int]] = {
    "void": ("void", 0),
    "bool": ("uint", 8),
    "char": ("int", 8),
    "int8_t": ("int", 8),
    "uint8_t": ("uint", 8),
    "short": ("int", 16),
    "int16_t": ("int", 16),
    "uint16_t": ("uint", 16),
    "int": ("int", 32),
    "int32_t": ("int", 32),
    "uint32_t": ("uint", 32),
    "long": ("int", 64),          # LP64 — the only ABI this tree runs
    "int64_t": ("int", 64),
    "long long": ("int", 64),
    "uint64_t": ("uint", 64),
    "unsigned": ("uint", 32),
    "unsigned int": ("uint", 32),
    "unsigned char": ("uint", 8),
    "unsigned short": ("uint", 16),
    "unsigned long": ("uint", 64),
    "unsigned long long": ("uint", 64),
    "size_t": ("uint", 64),
    "ssize_t": ("int", 64),
    "intptr_t": ("int", 64),
    "uintptr_t": ("uint", 64),
    "float": ("float", 32),
    "double": ("float", 64),
}


def parse_ctype(text: str) -> Optional[CType]:
    """Parse a C parameter/return type spelling into a CType."""
    spelled = " ".join(text.replace("*", " * ").split())
    toks = spelled.split()
    stars = toks.count("*")
    toks = [t for t in toks
            if t not in ("*", "const", "volatile", "struct", "enum")]
    if not toks:
        return None
    base = " ".join(toks)
    if base in _BASE_TYPES:
        kind, width = _BASE_TYPES[base]
        out = CType(kind, width, spelled=base)
    elif re.fullmatch(r"[A-Za-z_][\w:<>]*", base):
        out = CType("opaque", spelled=base)
    else:
        return None
    for _ in range(stars):
        out = CType("ptr", 64, pointee=out, spelled=spelled)
    return out


# ---------------------------------------------------------------------------
# Extracted entities
# ---------------------------------------------------------------------------


@dataclass
class CFunc:
    name: str
    path: str
    line: int
    ret: CType
    params: List[CType]
    param_names: List[str]
    is_definition: bool = False
    # `static` helpers inside an extern "C" block have internal
    # linkage: scanned for lock/blocking propagation, never part of
    # the exported ABI surface
    exported: bool = True
    # mutex identities this body locks (lock_guard/unique_lock/
    # pthread_mutex_lock arguments, normalized to the member name)
    locks: List[str] = field(default_factory=list)
    # unbounded blocking ops: (description, line)
    blocking: List[Tuple[str, int]] = field(default_factory=list)
    # calls PyGILState_Ensure (re-enters the interpreter)
    gil_line: int = 0
    calls: List[str] = field(default_factory=list)

    def sig(self) -> str:
        return (f"{self.ret.pretty()} {self.name}("
                + ", ".join(p.pretty() for p in self.params) + ")")


@dataclass
class CField:
    name: str
    ctype: CType
    count: int = 1          # >1 for fixed-size arrays
    line: int = 0


@dataclass
class CStruct:
    name: str
    path: str
    line: int
    fields: List[CField]
    mirrorable: bool        # every field fixed-width (no ptr/opaque)


@dataclass
class CxxIndex:
    files: List[str] = field(default_factory=list)
    # symbol -> every extern "C" occurrence (definitions + hand-copied
    # declarations in harnesses/clients — drift between them is a
    # finding in ffi.check_signatures)
    functions: Dict[str, List[CFunc]] = field(default_factory=dict)
    structs: Dict[str, CStruct] = field(default_factory=dict)
    constants: Dict[str, Tuple[int, str, int]] = field(
        default_factory=dict)                  # name -> (value, path, line)
    wire: Dict[str, Tuple[str, str, int]] = field(
        default_factory=dict)                  # name -> (fmt, path, line)
    # message types the native plane dispatches on / constructs, keyed
    # by type -> (path, line). `dispatch` covers ==/!= string compares
    # in handler loops; `sent` covers {"type": ...} literals the C++
    # itself builds. `surface_sent` restricts `sent` to files that
    # also dispatch (the native *plane*, not mere C++ clients).
    dispatch: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    sent: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    surface_sent: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    errors: List[Tuple[str, int, str]] = field(default_factory=list)

    def lookup(self, name: str) -> Optional[CFunc]:
        """The definition if one was parsed, else the first decl."""
        occ = self.functions.get(name)
        if not occ:
            return None
        for f in occ:
            if f.is_definition:
                return f
        return occ[0]


# ---------------------------------------------------------------------------
# Lexing helpers
# ---------------------------------------------------------------------------


def _blank(text: str) -> str:
    """Comments and string/char-literal contents replaced by spaces
    (newlines preserved) so structural parsing never trips on either."""
    out = list(text)
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                out[i] = " "
                i += 1
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            out[i] = out[i + 1] = " "
            i += 2
            while i < n and not (text[i] == "*" and i + 1 < n
                                 and text[i + 1] == "/"):
                if text[i] != "\n":
                    out[i] = " "
                i += 1
            if i < n:
                out[i] = out[i + 1] = " "
                i += 2
        elif c in "\"'":
            quote = c
            i += 1
            while i < n and text[i] != quote:
                if text[i] == "\\":
                    out[i] = " "
                    i += 1
                if i < n and text[i] != "\n":
                    out[i] = " "
                i += 1
            i += 1
        else:
            i += 1
    return "".join(out)


def _match_brace(text: str, open_pos: int) -> int:
    """Index just past the brace matching text[open_pos] ('{')."""
    depth = 0
    for i in range(open_pos, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def _lineno(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


_INT_RE = re.compile(r"^(0[xX][0-9a-fA-F]+|\d+)[uUlL]*$")


def _const_value(expr: str) -> Optional[int]:
    """Evaluate the constant-expression subset this tree uses:
    integer literals and `A << B` shifts of them."""
    expr = expr.strip().rstrip(";").strip()
    if "<<" in expr:
        lhs, _, rhs = expr.partition("<<")
        a, b = _const_value(lhs), _const_value(rhs)
        return a << b if a is not None and b is not None else None
    expr = expr.strip("() ")
    m = _INT_RE.match(expr)
    if not m:
        return None
    return int(m.group(1), 0)


# ---------------------------------------------------------------------------
# Per-file parsing
# ---------------------------------------------------------------------------

# matched against the blanked text, where the literal's content has
# been replaced by a space — accept both spellings
_EXTERN_RE = re.compile(r'extern\s+"[C ]"')
_LOCK_RE = re.compile(
    r"std::(?:lock_guard|unique_lock|scoped_lock)\s*<[^>]*>\s*\w+"
    r"\s*[({]\s*([^,)}]+)[,)}]")
_PTHREAD_LOCK_RE = re.compile(r"pthread_mutex_lock\s*\(\s*([^)]+)\)")
_JOIN_RE = re.compile(r"\b([\w.>-]+)\.join\s*\(\s*\)")
_CV_WAIT_RE = re.compile(r"\b([\w.>-]+)\.wait\s*\(")
_PTHREAD_WAIT_RE = re.compile(r"\bpthread_cond_wait\s*\(")
_GIL_RE = re.compile(r"\bPyGILState_Ensure\s*\(")
_CALL_RE = re.compile(r"\b([A-Za-z_]\w*)\s*\(")
_CPP_KEYWORDS = frozenset((
    "if while for switch return sizeof static_cast reinterpret_cast"
    " const_cast dynamic_cast new delete catch alignof defined assert"
    " snprintf memcpy memset malloc free close").split())

_DISPATCH_RE = re.compile(
    r'\b([A-Za-z_]\w*)\s*[!=]=\s*"([A-Za-z0-9_.-]+)"')
_SENT_RE = re.compile(r'\\"type\\":\s*\\"([A-Za-z0-9_.-]+)\\"')
_WIRE_RE = re.compile(r"//\s*cxx-wire:\s*([\w-]+)\s+(\S+)")
def _parse_field(raw: str) -> Optional[Tuple[str, str, Optional[str]]]:
    """``TYPE name;`` / ``TYPE name[COUNT];`` -> (type, name, count)."""
    raw = raw.strip()
    if not raw.endswith(";"):
        return None
    raw = raw[:-1].strip()
    count = None
    am = re.search(r"\[\s*(\w+)\s*\]$", raw)
    if am:
        count = am.group(1)
        raw = raw[:am.start()].strip()
    toks = raw.replace("*", " * ").split()
    if len(toks) < 2 or not re.fullmatch(r"[A-Za-z_]\w*", toks[-1]):
        return None
    return " ".join(toks[:-1]), toks[-1], count
_CONST_RE = re.compile(
    r"\b(?:static\s+)?(?:constexpr|const)\s+[\w:]+\s+"
    r"(\w+)\s*=\s*([^;]+);")
_DEFINE_RE = re.compile(r"^\s*#\s*define\s+(\w+)\s+(\S+)\s*$",
                        re.MULTILINE)
_ENUM_RE = re.compile(
    r"\benum\s+(?:class\s+)?\w*\s*(?::\s*[\w:]+\s*)?\{([^}]*)\}")


def _is_harness(path: str) -> bool:
    base = os.path.basename(path)
    return "stress" in base or "test" in base


def _split_params(text: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in text:
        if ch in "(<[":
            depth += 1
        elif ch in ")>]":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        out.append("".join(cur))
    return out


def _parse_signature(sig: str) -> Optional[Tuple[CType, str, List[CType],
                                                 List[str]]]:
    """``ret name(params)`` -> (ret, name, param types, param names)."""
    lp = sig.find("(")
    rp = sig.rfind(")")
    if lp < 0 or rp < lp:
        return None
    head = sig[:lp].replace("*", " * ").split()
    if len(head) < 2:
        return None
    name = head[-1]
    if not re.fullmatch(r"[A-Za-z_]\w*", name):
        return None
    ret = parse_ctype(" ".join(head[:-1]))
    if ret is None:
        return None
    params: List[CType] = []
    names: List[str] = []
    body = sig[lp + 1:rp].strip()
    if body and body != "void":
        for raw in _split_params(body):
            toks = raw.replace("*", " * ").split()
            # trailing identifier is the parameter name unless the
            # param is abstract (`void*`, `int`)
            pname = ""
            if (len(toks) >= 2 and re.fullmatch(r"[A-Za-z_]\w*", toks[-1])
                    and toks[-1] not in _BASE_TYPES):
                pname = toks[-1]
                toks = toks[:-1]
            pt = parse_ctype(" ".join(toks))
            if pt is None:
                return None
            params.append(pt)
            names.append(pname)
    return ret, name, params, names


def _scan_body(body: str, path: str, body_line: int, fn: CFunc) -> None:
    clean = body  # body already comes from the blanked text
    for m in _LOCK_RE.finditer(clean):
        lock = m.group(1).strip()
        lock = lock.split("->")[-1].split(".")[-1].strip("&* ")
        if lock:
            fn.locks.append(lock)
    for m in _PTHREAD_LOCK_RE.finditer(clean):
        lock = m.group(1).strip()
        lock = lock.split("->")[-1].split(".")[-1].strip("&* ")
        fn.locks.append(lock)
    for m in _JOIN_RE.finditer(clean):
        fn.blocking.append((f"joins `{m.group(1).split('.')[-1]}`",
                            body_line + clean.count("\n", 0, m.start())))
    for m in _CV_WAIT_RE.finditer(clean):
        # `.wait(` only — wait_for/wait_until are bounded, and the
        # futex path takes an explicit timeout argument.
        fn.blocking.append((
            f"waits on `{m.group(1).split('.')[-1].split('>')[-1]}` "
            f"with no timeout",
            body_line + clean.count("\n", 0, m.start())))
    if _PTHREAD_WAIT_RE.search(clean):
        m = _PTHREAD_WAIT_RE.search(clean)
        fn.blocking.append(("waits on a pthread condition with no "
                            "timeout",
                            body_line + clean.count("\n", 0, m.start())))
    m = _GIL_RE.search(clean)
    if m:
        fn.gil_line = body_line + clean.count("\n", 0, m.start())
    for m in _CALL_RE.finditer(clean):
        callee = m.group(1)
        if callee not in _CPP_KEYWORDS and callee != fn.name:
            fn.calls.append(callee)


def _parse_extern_block(clean: str, start: int, end: int, path: str,
                        idx: "CxxIndex") -> None:
    """Parse declarations/definitions between ``start`` and ``end``
    (the content of one ``extern "C" { ... }`` region)."""
    i = start
    while i < end:
        # next chunk: up to `;` at depth 0, or a `{` opening a body
        while i < end and clean[i] in " \t\r\n;":
            i += 1
        if i >= end:
            break
        j, depth = i, 0
        body_open = -1
        while j < end:
            c = clean[j]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            elif c == ";" and depth == 0:
                break
            elif c == "{" and depth == 0:
                body_open = j
                break
            j += 1
        sig_text = clean[i:j].strip()
        line = _lineno(clean, i)
        if sig_text.startswith("#"):            # preprocessor line
            i = clean.index("\n", i) + 1 if "\n" in clean[i:j] else j + 1
            continue
        if body_open >= 0:
            body_end = _match_brace(clean, body_open)
        exported = True
        if re.match(r"(static|inline)\b", sig_text):
            exported = False
            sig_text = re.sub(r"^(?:static|inline)\s+", "", sig_text)
        parsed = _parse_signature(sig_text) if sig_text else None
        if parsed is None:
            if sig_text and "(" in sig_text:
                idx.errors.append((
                    path, line,
                    f'unparseable extern "C" declaration: '
                    f'`{" ".join(sig_text.split())[:80]}`'))
            i = (body_end if body_open >= 0 else j + 1)
            continue
        ret, name, params, pnames = parsed
        fn = CFunc(name, path, line, ret, params, pnames,
                   is_definition=body_open >= 0, exported=exported)
        if body_open >= 0:
            _scan_body(clean[body_open:body_end], path,
                       _lineno(clean, body_open), fn)
            i = body_end
        else:
            i = j + 1
        idx.functions.setdefault(name, []).append(fn)


def _parse_structs(clean: str, path: str, idx: "CxxIndex") -> None:
    for m in re.finditer(r"\bstruct\s+(\w+)\s*\{", clean):
        name = m.group(1)
        body_start = m.end() - 1
        body_end = _match_brace(clean, body_start)
        body = clean[body_start + 1:body_end - 1]
        if "{" in body:            # nested types/methods — not a mirror
            continue
        line = _lineno(clean, m.start())
        fields: List[CField] = []
        mirrorable = True
        for off, raw in enumerate(body.split("\n")):
            raw = raw.strip()
            if not raw or raw.startswith("#"):
                continue
            fm = _parse_field(raw)
            if fm is None:
                if "(" in raw:      # method/constructor
                    mirrorable = False
                continue
            ftext, fname, fcount = fm
            ftype = parse_ctype(ftext)
            if ftype is None:
                mirrorable = False
                continue
            count = 1
            if fcount:
                count = _const_value(fcount)
                if count is None:
                    count = idx.constants.get(fcount, (0,))[0]
                if not count:
                    mirrorable = False
                    count = 1
            if ftype.kind == "opaque":
                sub = idx.structs.get(ftype.spelled)
                if sub is None or not sub.mirrorable:
                    mirrorable = False
            elif ftype.kind == "ptr":
                mirrorable = False
            fields.append(CField(fname, ftype, count,
                                 _lineno(clean, m.end()) + off))
        if fields and name not in idx.structs:
            idx.structs[name] = CStruct(name, path, line, fields,
                                        mirrorable)


def _parse_constants(clean: str, path: str, idx: "CxxIndex") -> None:
    for m in _CONST_RE.finditer(clean):
        val = _const_value(m.group(2))
        if val is not None and m.group(1) not in idx.constants:
            idx.constants[m.group(1)] = (val, path,
                                         _lineno(clean, m.start()))
    for m in _DEFINE_RE.finditer(clean):
        val = _const_value(m.group(2))
        if val is not None and m.group(1) not in idx.constants:
            idx.constants[m.group(1)] = (val, path,
                                         _lineno(clean, m.start()))
    for m in _ENUM_RE.finditer(clean):
        nxt = 0
        for part in m.group(1).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                ename, _, expr = part.partition("=")
                val = _const_value(expr)
                if val is None:
                    continue
                nxt = val
            else:
                ename = part
                val = nxt
            ename = ename.strip()
            if re.fullmatch(r"\w+", ename) and ename not in idx.constants:
                idx.constants[ename] = (
                    val, path,
                    _lineno(clean, m.start()) +
                    m.group(1)[:m.group(1).find(ename)].count("\n"))
            nxt += 1


def parse_file(path: str, idx: CxxIndex) -> None:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            raw = f.read()
    except OSError as e:
        idx.errors.append((path, 0, f"unreadable: {e}"))
        return
    idx.files.append(path)
    clean = _blank(raw)
    harness = _is_harness(path)

    if not harness:
        _parse_constants(clean, path, idx)
        _parse_structs(clean, path, idx)

    pos = 0
    while True:
        m = _EXTERN_RE.search(clean, pos)
        if m is None:
            break
        after = clean[m.end():].lstrip()
        if after.startswith("{"):
            open_pos = clean.index("{", m.end())
            end = _match_brace(clean, open_pos)
            _parse_extern_block(clean, open_pos + 1, end - 1, path, idx)
            pos = end
        else:
            semi = clean.find(";", m.end())
            semi = len(clean) if semi < 0 else semi
            _parse_extern_block(clean, m.end(), semi + 1, path, idx)
            pos = semi + 1

    if harness:
        return

    # wire-frame annotations live in comments -> raw text
    for m in _WIRE_RE.finditer(raw):
        if m.group(1) not in idx.wire:
            idx.wire[m.group(1)] = (m.group(2), path,
                                    _lineno(raw, m.start()))
    has_dispatch = False
    for m in _DISPATCH_RE.finditer(raw):
        var, mtype = m.group(1), m.group(2)
        if "type" not in var.lower():
            continue
        has_dispatch = True
        idx.dispatch.setdefault(mtype, (path, _lineno(raw, m.start())))
    for m in _SENT_RE.finditer(raw):
        site = (path, _lineno(raw, m.start()))
        idx.sent.setdefault(m.group(1), site)
        if has_dispatch:
            idx.surface_sent.setdefault(m.group(1), site)


# ---------------------------------------------------------------------------
# Index construction
# ---------------------------------------------------------------------------


def find_sources(root: str) -> List[str]:
    """C/C++ sources belonging to the tree rooted at ``root``: files
    inside the root itself, plus the conventional sibling ``src/`` and
    ``cpp/`` directories (the Python package and its native plane are
    siblings in this repo: ``ray_tpu/`` next to ``src/``)."""
    roots = [root]
    parent = os.path.dirname(os.path.abspath(root))
    for sib in ("src", "cpp"):
        cand = os.path.join(parent, sib)
        if os.path.isdir(cand) and os.path.abspath(cand) != \
                os.path.abspath(root):
            roots.append(cand)
    out: List[str] = []
    for r in roots:
        for dirpath, dirnames, filenames in os.walk(r):
            dirnames[:] = [d for d in dirnames
                           if d not in (".git", "__pycache__")]
            for fn in sorted(filenames):
                if fn.endswith(_EXTS):
                    out.append(os.path.join(dirpath, fn))
    return out


def _propagate(idx: CxxIndex) -> None:
    """Close blocking/lock info over the intra-C++ call graph so an
    export that stops via a static helper still reads as blocking."""
    changed = True
    rounds = 0
    while changed and rounds < 10:
        changed = False
        rounds += 1
        for occ in idx.functions.values():
            for fn in occ:
                if not fn.is_definition:
                    continue
                for callee in fn.calls:
                    tgt = idx.lookup(callee)
                    if tgt is None or tgt is fn or not tgt.is_definition:
                        continue
                    for b in tgt.blocking:
                        via = (f"{b[0]} (via {callee}())", b[1])
                        if via not in fn.blocking and \
                                b not in fn.blocking:
                            fn.blocking.append(via)
                            changed = True
                    if tgt.gil_line and not fn.gil_line:
                        fn.gil_line = tgt.gil_line
                        changed = True


def build(root: str, files: Optional[List[str]] = None) -> CxxIndex:
    idx = CxxIndex()
    srcs = files if files is not None else find_sources(root)
    # constants/structs first so struct fields can resolve array sizes
    # and nested struct types declared in the same pass; two passes
    # keep it order-independent.
    for path in srcs:
        parse_file(path, idx)
    if idx.files:
        reparse = CxxIndex()
        reparse.constants = idx.constants
        for path in list(idx.files):
            if not _is_harness(path):
                _parse_structs(_blank(_read(path)), path, reparse)
        idx.structs = reparse.structs
    _propagate(idx)
    return idx


def _read(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return ""


# ---------------------------------------------------------------------------
# Standalone entry point (`make -C src lint`)
# ---------------------------------------------------------------------------


def main(argv: List[str]) -> int:
    targets: List[str] = []
    for a in argv or ["."]:
        if os.path.isdir(a):
            for dirpath, dirnames, filenames in os.walk(a):
                dirnames[:] = [d for d in dirnames
                               if d not in (".git", "__pycache__")]
                for fn in sorted(filenames):
                    if fn.endswith(_EXTS):
                        targets.append(os.path.join(dirpath, fn))
        else:
            targets.append(a)
    idx = build(".", files=targets)
    ndef = sum(1 for occ in idx.functions.values()
               for f in occ if f.is_definition and f.exported)
    ndecl = sum(1 for occ in idx.functions.values()
                for f in occ if f.exported) - ndef
    print(f"cxx: {len(idx.files)} file(s): {ndef} extern \"C\" "
          f"definition(s) (+{ndecl} redeclarations), "
          f"{len(idx.structs)} struct layout(s), "
          f"{len(idx.constants)} constant(s), "
          f"{len(idx.wire)} wire frame(s), "
          f"{len(idx.dispatch)} dispatched + {len(idx.sent)} sent "
          f"message type(s)")
    for name in sorted(idx.functions):
        fn = idx.lookup(name)
        if fn.exported:
            print(f"  {fn.sig()}  "
                  f"[{os.path.basename(fn.path)}:{fn.line}]")
    for path, line, msg in idx.errors:
        print(f"{path}:{line}: cxx-parse-error: {msg}",
              file=sys.stderr)
    return 1 if idx.errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
