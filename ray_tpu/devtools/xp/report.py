"""Machine-readable reports (JSON / SARIF 2.1.0) and the baseline.

Whole-program findings have no single line to hang an inline
suppression on, so their suppression mechanism is a checked-in
baseline file::

    {
      "version": 1,
      "entries": [
        {"rule": "proto-orphan-handled",
         "path": "ray_tpu/node/daemon.py",
         "contains": "task_xlang",
         "reason": "sent by the C++ client (cpp/src/client.cc)"}
      ]
    }

An entry matches a finding when the rule is equal, the finding's path
ends with ``path``, and ``contains`` is a substring of the message.
``reason`` is mandatory — an entry without one is reported, and so is
an entry that matches nothing (``stale-baseline``), so the file can
only shrink as findings are fixed.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def apply_baseline(findings: list, baseline_path: str) -> list:
    """Mark baselined findings suppressed (annotating the reason);
    returns extra findings for malformed/stale entries."""
    from ..raylint import Finding

    extra: List[Finding] = []
    try:
        with open(baseline_path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return extra
    except (OSError, ValueError) as e:
        return [Finding(baseline_path, 0, "stale-baseline",
                        f"unreadable baseline: {e}")]
    for i, entry in enumerate(data.get("entries", [])):
        rule = entry.get("rule", "")
        path_sfx = entry.get("path", "")
        contains = entry.get("contains", "")
        reason = entry.get("reason", "").strip()
        if not reason:
            extra.append(Finding(
                baseline_path, 0, "stale-baseline",
                f"baseline entry #{i} ({rule}: {contains!r}) has no "
                f"reason — every suppression must say why"))
            continue
        matched = False
        for f in findings:
            if (f.rule == rule and f.path.endswith(path_sfx)
                    and contains in f.message and not f.suppressed):
                f.suppressed = True
                f.message += f" [baselined: {reason}]"
                matched = True
        if not matched:
            extra.append(Finding(
                baseline_path, 0, "stale-baseline",
                f"baseline entry #{i} ({rule}: {contains!r}) matches "
                f"no finding — remove it (the hazard is fixed) or fix "
                f"the entry"))
    return extra


def to_json(findings: list, inventory: Optional[list] = None) -> str:
    active = [f for f in findings if not f.suppressed]
    payload = {
        "findings": [f.to_dict() for f in findings],
        "total": len(active),
        "suppressed": sum(1 for f in findings if f.suppressed),
    }
    if inventory is not None:
        payload["protocol"] = inventory
    return json.dumps(payload, indent=2)


def _rel(path: str) -> str:
    try:
        rel = os.path.relpath(path)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel.replace(os.sep, "/")


def to_sarif(findings: list, rule_docs: Dict[str, str]) -> str:
    rules_seen = sorted({f.rule for f in findings})
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "note" if f.suppressed else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _rel(f.path)},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "external"}]
        results.append(result)
    doc = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "raylint",
                "informationUri":
                    "https://example.invalid/ray_tpu/devtools",
                "rules": [{
                    "id": r,
                    "shortDescription":
                        {"text": rule_docs.get(r, r)},
                } for r in rules_seen],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def inventory_table(inventory: Iterable[dict]) -> str:
    """The wire-protocol inventory as a markdown table. The "native
    plane" column marks dispatch-socket ops the C++ front end
    (src/node_dispatch.cc) also implements — the annotation
    (protocol.NATIVE_PLANE) is derived from the parsed C++ dispatch
    arms and send sites and checked by xp-xlang-protocol, and the
    column carries the C++ site the extractor found."""
    lines = [
        "| type | senders | handlers | fields | native plane |",
        "|------|---------|----------|--------|--------------|",
    ]
    for row in inventory:
        def sites(key):
            items = [_rel(s) for s in row[key]]
            if not items:
                return "—"
            shown = ", ".join(items[:3])
            if len(items) > 3:
                shown += f", … ({len(items)} total)"
            return shown
        native = row.get("native", "—")
        if row.get("native_site"):
            native = f"{native} ({_rel(row['native_site'])})"
        lines.append(
            f"| `{row['type']}` | {sites('senders')} | "
            f"{sites('handlers')} | "
            f"{', '.join(row['fields']) or '—'} | "
            f"{native} |")
    return "\n".join(lines)
