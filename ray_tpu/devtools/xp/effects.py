"""Interprocedural effect-and-purity analysis for graph capture.

A captured task graph replays DRIVER code as pre-encoded frames: the
submissions re-fire, but the Python between them does not re-run. Any
side effect in that Python is therefore executed at CAPTURE time only
— the first iteration performs it, every replayed iteration skips it.
This pass classifies every function reachable from a capture-intent
entry point (``@ray_tpu.graphable`` defs and ``compile_dag``/
``experimental_compile`` callers — plain ``.bind()`` builders declare
no replay intent and are exempt) for the effect classes that make
replay unsound, and flags each as ``xp-graph-unsafe-capture``:

- **mutation** — assignment to ``self.<attr>`` or to a declared
  ``global``/``nonlocal`` name: iteration counters, version bumps and
  caches freeze at their capture-time values.
- **clock** — wall-time reads (``time.time``/``perf_counter``/
  ``monotonic``, ``datetime.now``): timings and timeouts measured at
  capture get baked into every replay.
- **random** — ``random.*`` / ``np.random.*`` / RNG-object draws /
  ``uuid.uuid4``/``secrets.*``: the capture iteration's samples replay
  verbatim (an RLHF pipeline would train on the same prompts forever).
- **io** — ``open()``/``print``/``subprocess``/file writes: logs and
  checkpoints stop happening after the first iteration.

Data-dependent control flow on ``get()``-derived values is the fifth
classification from the effect model; it is *shape*-changing rather
than merely state-changing, so :mod:`.graphcap` owns its rule
(``xp-graph-shape-drift``) where the guarded submissions are visible.

Findings aggregate per (reached function, effect class) with up to
three line witnesses — one reviewable row per decision, not one per
line — and carry the call chain from the entry, jitlint-style. The
reachable set is pruned at the runtime plane (see
:func:`graphcap.capture_reach`): replay replaces the dispatch
machinery, so only driver code is judged.

Every intentional effect belongs in the baseline with a reason that
names the replay plan for it (feed it as frame input, hoist it out of
the captured region, or accept the loss) — that reviewed list IS the
capture contract for the replay PR.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .dataflow import CallGraph, FuncInfo, RemoteResolver
from .graphcap import capture_reach, find_entries
from .index import ProjectIndex

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

_CLOCK_ATTRS = {
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("time", "time_ns"), ("time", "monotonic_ns"),
    ("time", "perf_counter_ns"), ("time", "clock_gettime"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("date", "today"),
}
_RANDOM_RECVS = {"random", "secrets"}
_RNG_METHODS = {"integers", "random", "normal", "uniform", "choice",
                "permutation", "shuffle", "standard_normal", "randint"}
_UUID_ATTRS = {"uuid1", "uuid4"}
_IO_BARE = {"open", "print", "input"}
_IO_ATTRS = {
    ("os", "remove"), ("os", "unlink"), ("os", "rename"),
    ("os", "makedirs"), ("os", "mkdir"), ("os", "rmdir"),
    ("os", "system"), ("os", "urandom"),
    ("subprocess", "run"), ("subprocess", "Popen"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("shutil", "rmtree"), ("shutil", "copy"), ("shutil", "copytree"),
}
_IO_METHODS = {"write_text", "write_bytes"}

_WHY = {
    "mutation": ("replay skips the Python between submissions, so the "
                 "state freezes at its capture-time value"),
    "clock": ("the capture iteration's timestamp is baked into every "
              "replay — timings, timeouts and rate metrics go stale"),
    "random": ("the capture iteration's samples replay verbatim — "
               "every 'random' draw repeats forever"),
    "io": ("the write/log happens at capture time only; replayed "
           "iterations are silent"),
}


def _dotted_tail(expr: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    if isinstance(expr, ast.Name):
        return None, expr.id
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name):
            return expr.value.id, expr.attr
        if isinstance(expr.value, ast.Attribute):
            return expr.value.attr, expr.attr
    return None, None


def classify(fi: FuncInfo) -> Dict[str, List[Tuple[int, str]]]:
    """effect class -> [(line, witness)] for one function body.
    Nested defs are separate functions in the call graph and are
    skipped; lambdas share the body and are included."""
    out: Dict[str, List[Tuple[int, str]]] = {}

    def add(kind: str, line: int, what: str) -> None:
        out.setdefault(kind, []).append((line, what))

    fn = fi.node
    declared: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, _FUNC_NODES) and n is not fn:
            continue
        if isinstance(n, (ast.Global, ast.Nonlocal)):
            declared.update(n.names)
    for n in ast.walk(fn):
        if isinstance(n, _FUNC_NODES) and n is not fn:
            continue
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    add("mutation", n.lineno, f"self.{t.attr} assigned")
                elif (isinstance(t, ast.Name) and t.id in declared):
                    add("mutation", n.lineno,
                        f"global/nonlocal {t.id} assigned")
        elif isinstance(n, ast.Call):
            _classify_call(n, add)
    for kind in out:
        out[kind].sort()
    return out


def _classify_call(call: ast.Call, add) -> None:
    recv, name = _dotted_tail(call.func)
    if recv is None:
        if name in _IO_BARE:
            add("io", call.lineno, f"{name}() call")
        elif name in ("perf_counter", "monotonic", "time_ns"):
            add("clock", call.lineno, f"{name}() call")
        return
    if (recv, name) in _CLOCK_ATTRS:
        add("clock", call.lineno, f"{recv}.{name}() call")
        return
    if (recv, name) in _IO_ATTRS or name in _IO_METHODS:
        add("io", call.lineno, f"{recv}.{name}() call")
        return
    if recv in _RANDOM_RECVS or (recv == "uuid" and name in _UUID_ATTRS):
        add("random", call.lineno, f"{recv}.{name}() call")
        return
    # RNG objects: self._rng.integers(...), rng.choice(...)
    if name in _RNG_METHODS and "rng" in recv.lower():
        add("random", call.lineno, f"{recv}.{name}() draw")


def _chain_str(chain: List[str]) -> str:
    shown = chain if len(chain) <= 4 else chain[:2] + ["..."] + chain[-1:]
    return " -> ".join(q.rsplit(".", 1)[-1] + "()" for q in shown)


def check(idx: ProjectIndex, graph: Optional[CallGraph] = None,
          resolver: Optional[RemoteResolver] = None,
          only: Optional[Set[str]] = None) -> List:
    from ..raylint import Finding

    resolver = resolver or RemoteResolver(idx)
    graph = graph or CallGraph(idx)
    entries = [e for e in find_entries(idx, resolver)
               if e.kind in ("graphable", "compile")]
    effects_memo: Dict[str, Dict[str, List[Tuple[int, str]]]] = {}
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()   # (path, anchor, kind)

    for entry in entries:
        reach = capture_reach(graph, idx, entry.fi.qual)
        for qual, chain in sorted(reach.items()):
            fi = idx.functions.get(qual)
            if fi is None:
                continue
            # reachability stays global; the scan of each reached
            # body is scoped to the diff
            if only is not None and fi.path not in only:
                continue
            eff = effects_memo.get(qual)
            if eff is None:
                eff = classify(fi)
                effects_memo[qual] = eff
            for kind, witnesses in sorted(eff.items()):
                anchor = witnesses[0][0]
                key = (fi.path, anchor, kind)
                if key in seen:
                    continue
                seen.add(key)
                shown = ", ".join(
                    f"line {ln} ({what})"
                    for ln, what in witnesses[:3])
                more = (f" and {len(witnesses) - 3} more"
                        if len(witnesses) > 3 else "")
                via = ""
                if len(chain) > 1:
                    via = f" [captured via {_chain_str(chain)}]"
                findings.append(Finding(
                    fi.path, anchor, "xp-graph-unsafe-capture",
                    f"{kind} effect in {fi.name}() inside the "
                    f"captured graph of entry {entry.fi.name}() at "
                    f"{entry.fi.path.rsplit('/', 1)[-1]}:"
                    f"{entry.line}{via} — {_WHY[kind]}; witnesses: "
                    f"{shown}{more}"))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
