"""ObjectRef lifetime analysis: leaks and serialization anti-patterns.

Every ObjectRef pins its object in the store until the ref is
dropped AND consumed; a ref that is produced and then forgotten is a
silent leak (the object lives until the producer process exits), and
a ``get()`` per ref inside a loop serializes a fan-out behind one
round-trip per element. Def-use over each function body (statement
order, provenance from the dataflow engine) finds both:

- **xp-ref-leak** — a ref produced by ``put()`` / ``.remote()`` that
  is never *consumed*: not passed to ``get``/``wait``/``cancel``, not
  passed as any call argument, not returned/yielded, not stored into
  a container/attribute, not even loaded again. Two shapes fire:
  a bare expression statement discarding the result
  (``h.update.remote(x)`` fire-and-forget — the returned ref pins the
  result object forever), and a bound name with zero later loads
  anywhere in the function (nested defs count as loads). A load of
  any kind counts as consumption — passing a ref onward transfers
  ownership interprocedurally, and judging the consumer again there
  keeps the rule per-function without losing the chain.
  ``num_returns=0`` submissions return None and are exempt, and that
  is also the documented fix for intentional fire-and-forget;
  anything else intentional belongs in the baseline with a reason.
- **xp-ref-get-in-loop** — ``for r in refs: ... get(r)`` where
  ``refs`` is known (by provenance) to hold remote-call results —
  a comprehension over ``.remote()``, a literal list of remote
  calls, or a list that only ``append``s remote results: each
  iteration blocks on one ref while the rest sit ready, so a fan-out
  of N tasks costs N sequential round-trips. ``get(refs)`` fetches
  them in one call (or ``wait()`` harvests them as they finish).

Only refs born inside the function are judged — parameters and
attributes may be owned elsewhere, and a whole-program ownership
model would drown the rule in uncertainty.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .dataflow import (FuncInfo, RemoteResolver,
                       _iter_calls, _resolve_name, _stmt_bodies)
from .index import ProjectIndex

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)

_RAY_MODULES = ("ray", "ray_tpu", "rt")


def _bare_is_rays(name: str, scope: FuncInfo,
                  idx: ProjectIndex) -> bool:
    """A bare ``put``/``get`` name counts as the ray API only when
    nothing in the project shadows it: a nested/module-level def named
    ``put`` (the dashboard has one) must win over the import."""
    r = _resolve_name(name, scope, idx)
    if r is None:
        return True       # imported from outside the index
    return isinstance(r, FuncInfo) and r.path.endswith("__init__.py")


def _is_put(call: ast.Call, scope: FuncInfo,
            idx: ProjectIndex) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "put":
        return _bare_is_rays("put", scope, idx)
    return (isinstance(f, ast.Attribute) and f.attr == "put"
            and isinstance(f.value, ast.Name)
            and f.value.id in _RAY_MODULES)


def _is_remote(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "remote")


def _is_get(call: ast.Call, scope: FuncInfo,
            idx: ProjectIndex) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "get":
        return _bare_is_rays("get", scope, idx)
    return (isinstance(f, ast.Attribute) and f.attr == "get"
            and isinstance(f.value, ast.Name)
            and f.value.id in _RAY_MODULES)


class _FnScan:
    """One statement-ordered pass over a function body."""

    def __init__(self, fi: FuncInfo, resolver: RemoteResolver,
                 idx: ProjectIndex):
        self.fi = fi
        self.resolver = resolver
        self.idx = idx
        self.findings: List[tuple] = []   # (line, rule, message)
        # names that are known ref containers (lists of remote results)
        self.ref_containers: Set[str] = set()
        self.env = resolver.seed_env(fi)
        # every Name load anywhere in the fn — a load in a nested
        # def/lambda/comprehension still counts as a use, and an
        # explicit `del r` is a deliberate early free, not a leak
        self.all_loads = {
            n.id for n in ast.walk(fi.node)
            if isinstance(n, ast.Name)
            and isinstance(n.ctx, (ast.Load, ast.Del))}

    # -- classification -----------------------------------------------

    def _ref_producing(self, value: ast.AST) -> Optional[str]:
        """A description when `value` produces ObjectRef(s) whose
        loss would leak, else None."""
        if not isinstance(value, ast.Call):
            return None
        if _is_put(value, self.fi, self.idx):
            return "put()"
        if _is_remote(value):
            site = self.resolver.site(value, self.fi, self.env)
            if site is None:
                return None       # unresolved: stay silent
            if site.kind == "actor_create":
                return None       # handles are not refs
            nr = site.options.get("num_returns")
            if isinstance(nr, ast.Constant) and nr.value == 0:
                return None       # declared fire-and-forget
            return f"{site.describe()}()"
        return None

    def _container_of_refs(self, value: ast.AST) -> bool:
        if isinstance(value, (ast.ListComp, ast.SetComp)):
            return self._yields_ref(value.elt)
        if isinstance(value, (ast.List, ast.Set, ast.Tuple)):
            return bool(value.elts) and all(
                self._yields_ref(e) for e in value.elts)
        return False

    def _yields_ref(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Call) and (
                _is_remote(e) or _is_put(e, self.fi, self.idx)):
            return self._ref_producing(e) is not None
        return False

    # -- walk ---------------------------------------------------------

    def run(self) -> List[tuple]:
        self._walk(list(getattr(self.fi.node, "body", [])))
        return self.findings

    def _walk(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            self._stmt(stmt)
            for body in _stmt_bodies(stmt):
                self._walk(body)

    def _stmt(self, stmt: ast.stmt) -> None:
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, (ast.stmt, ast.excepthandler)):
                self.resolver.bind_comps(self.env, child, self.fi)
        if isinstance(stmt, ast.Assign):
            self._bind(stmt)
            return
        if isinstance(stmt, ast.AnnAssign):
            self.resolver.bind(self.env, stmt, self.fi)
            return
        if isinstance(stmt, ast.Expr):
            v = stmt.value
            self.resolver.bind_append(self.env, v, self.fi)
            # `refs.append(f.remote(x))` grows a ref container
            if (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr in ("append", "add")
                    and isinstance(v.func.value, ast.Name)
                    and v.args and self._yields_ref(v.args[0])):
                self.ref_containers.add(v.func.value.id)
                return
            desc = self._ref_producing(v)
            if desc is not None:
                self.findings.append((
                    stmt.lineno, "xp-ref-leak",
                    f"result of {desc} is discarded — the returned "
                    f"ObjectRef pins the task's result in the store "
                    f"with no way to ever free or read it; bind and "
                    f"consume the ref, or declare "
                    f".options(num_returns=0) for true "
                    f"fire-and-forget"))
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.resolver.bind_for(self.env, stmt, self.fi)
            self._check_get_loop(stmt)

    def _bind(self, stmt: ast.Assign) -> None:
        v = stmt.value
        # provenance first: handles/aliases/handle lists feed site()
        self.resolver.bind(self.env, stmt, self.fi)
        if isinstance(v, ast.Call) and _is_remote(v):
            site = self.resolver.site(v, self.fi, self.env)
            if site is not None and site.kind == "actor_create":
                return
        desc = self._ref_producing(v)
        is_container = self._container_of_refs(v)
        for tgt in stmt.targets:
            if not isinstance(tgt, ast.Name):
                continue        # stored into container/attr: consumed
            self.ref_containers.discard(tgt.id)
            if is_container:
                self.ref_containers.add(tgt.id)
            if desc is not None and tgt.id not in self.all_loads:
                self.findings.append((
                    stmt.lineno, "xp-ref-leak",
                    f"ref from {desc} bound to `{tgt.id}` is never "
                    f"consumed (no get/wait, never passed on, "
                    f"returned, or stored) — the result object stays "
                    f"pinned in the store until this process exits; "
                    f"consume the ref or declare "
                    f".options(num_returns=0)"))

    def _check_get_loop(self, stmt: ast.stmt) -> None:
        it = stmt.iter
        iter_name = it.id if isinstance(it, ast.Name) else None
        if iter_name is None or iter_name not in self.ref_containers:
            return
        tgt = stmt.target
        if not isinstance(tgt, ast.Name):
            return
        for call in _iter_calls_in_body(stmt.body):
            if not _is_get(call, self.fi, self.idx):
                continue
            if any(isinstance(a, ast.Name) and a.id == tgt.id
                   for a in call.args):
                self.findings.append((
                    call.lineno, "xp-ref-get-in-loop",
                    f"get({tgt.id}) inside a loop over "
                    f"`{iter_name}` serializes the fan-out: each "
                    f"iteration blocks on ONE ref while the rest sit "
                    f"ready — fetch once with get({iter_name}), or "
                    f"harvest completions with wait()"))
                return


def _iter_calls_in_body(stmts: List[ast.stmt]):
    for stmt in stmts:
        if isinstance(stmt, _SKIP_NODES):
            continue
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, ast.stmt):
                yield from _iter_calls(child)
        for body in _stmt_bodies(stmt):
            yield from _iter_calls_in_body(body)


def check(idx: ProjectIndex, resolver: Optional[RemoteResolver] = None,
          only: Optional[Set[str]] = None) -> List:
    from ..raylint import Finding

    resolver = resolver or RemoteResolver(idx)
    findings: List[Finding] = []
    for fi in idx.all_functions():
        if only is not None and fi.path not in only:
            continue
        for line, rule, msg in _FnScan(fi, resolver, idx).run():
            findings.append(Finding(fi.path, line, rule, msg))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings
