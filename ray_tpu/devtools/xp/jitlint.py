"""jit-purity / host-sync detection over the traced call graph.

``jax.jit`` traces a function once per (shape, static-arg) signature;
everything the trace reaches runs under tracer semantics. Three bug
classes hide there, all invisible per-file because the offending code
usually sits in a helper far from the ``@jit`` line:

- **xp-jit-host-sync** — a device->host synchronization inside traced
  code: ``.item()`` / ``.tolist()`` / ``np.asarray`` / ``np.array`` /
  ``jax.device_get`` / ``float()``/``int()``/``bool()`` on a traced
  parameter, or plain ``print`` of traced values. Under trace these
  either raise ``ConcretizationTypeError`` at compile time or — worse
  — silently bake a trace-time constant into the compiled program.
  ``jax.debug.print`` / ``jax.debug.callback`` /
  ``io_callback`` are the sanctioned escapes and do not fire.
- **xp-jit-impure-mutation** — assignment to ``self.<attr>`` or to a
  ``global``/``nonlocal`` name inside traced code. The mutation runs
  at TRACE time only: the first call per signature performs it, every
  later call skips it (the compiled program has no Python), so state
  drifts apart between the first and the N-th step.
- **xp-jit-static-args** — a ``static_argnums`` index out of range of
  the target's positional parameters, a ``static_argnames`` name that
  is not a parameter (both: the drift class where a refactor reorders
  parameters and the decorator silently pins the WRONG argument —
  retrace storms or shape errors), or a call site passing a
  list/dict/set literal at a static position (unhashable ->
  TypeError at first call).

Entry points: defs decorated ``@jax.jit`` / ``@jit`` / ``@pjit`` /
``@partial(jax.jit, ...)``, and project functions wrapped by a
``jax.jit(fn, ...)`` call expression. The traced region is the
entry's reachable set over the resolved call graph; each finding
carries the entry and call chain so the reader can see HOW the line
is reached under trace.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .dataflow import CallGraph, FuncInfo, resolve_value
from .index import ProjectIndex

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

_JIT_NAMES = {"jit", "pjit"}
_HOST_SYNC_METHODS = {"item", "tolist"}
_HOST_ARRAY_FUNCS = {"asarray", "array"}   # on numpy receivers
_NUMPY_NAMES = {"np", "numpy", "onp"}
_CAST_BUILTINS = {"float", "int", "bool"}


def _dotted_tail(expr: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(receiver name, attr) for ``recv.attr`` / (None, name) for a
    bare name."""
    if isinstance(expr, ast.Name):
        return None, expr.id
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name):
            return expr.value.id, expr.attr
        if isinstance(expr.value, ast.Attribute):
            return expr.value.attr, expr.attr
    return None, None


def _is_jit_callable(expr: ast.AST) -> bool:
    recv, name = _dotted_tail(expr)
    return name in _JIT_NAMES and recv in (None, "jax")


@dataclass
class JitEntry:
    fi: FuncInfo
    line: int
    static_argnums: Optional[List[int]]      # None = dynamic/unknown
    static_argnames: Optional[List[str]]


def _const_int_tuple(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return out
    return None


def _const_str_tuple(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return out
    return None


def _jit_kwargs(call: ast.Call) -> Tuple[Optional[List[int]],
                                         Optional[List[str]]]:
    nums = names = None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _const_int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            names = _const_str_tuple(kw.value)
    return nums, names


def find_entries(idx: ProjectIndex) -> List[JitEntry]:
    entries: List[JitEntry] = []
    seen: Set[str] = set()

    def add(fi: FuncInfo, line: int, nums, names) -> None:
        if fi.qual in seen:
            return
        seen.add(fi.qual)
        entries.append(JitEntry(fi, line, nums, names))

    for fi in idx.all_functions():
        for dec in getattr(fi.node, "decorator_list", []):
            if _is_jit_callable(dec):
                add(fi, dec.lineno, None, None)
            elif isinstance(dec, ast.Call):
                if _is_jit_callable(dec.func):
                    nums, names = _jit_kwargs(dec)
                    add(fi, dec.lineno, nums, names)
                    continue
                _, fname = _dotted_tail(dec.func)
                if (fname == "partial" and dec.args
                        and _is_jit_callable(dec.args[0])):
                    nums, names = _jit_kwargs(dec)
                    add(fi, dec.lineno, nums, names)
        # jax.jit(fn, ...) wrap-call form
        for n in ast.walk(fi.node):
            if not (isinstance(n, ast.Call)
                    and _is_jit_callable(n.func) and n.args):
                continue
            target = n.args[0]
            if (isinstance(target, ast.Call)
                    and isinstance(target.func, ast.Name)
                    and target.func.id == "partial" and target.args):
                target = target.args[0]
            r = resolve_value(target, fi, idx)
            if isinstance(r, FuncInfo):
                nums, names = _jit_kwargs(n)
                add(r, n.lineno, nums, names)
    return entries


def _chain_str(chain: List[str]) -> str:
    shown = chain if len(chain) <= 4 else chain[:2] + ["..."] + chain[-1:]
    return " -> ".join(q.rsplit(".", 1)[-1] + "()" for q in shown)


class _TracedScan:
    """Host-sync + impurity findings inside one traced function."""

    def __init__(self, fi: FuncInfo, entry: JitEntry,
                 chain: List[str]):
        self.fi = fi
        self.entry = entry
        self.chain = chain
        self.out: List[tuple] = []
        # traced parameter names: the entry's non-static positionals
        # (for callees everything is possibly traced; we only apply
        # the float()/int() cast check to the ENTRY's own params,
        # where tracedness is certain)
        self.traced_params: Set[str] = set()
        if fi is entry.fi:
            pos = fi.param_names()
            static = set(entry.static_argnums or [])
            statics = {pos[i] for i in static if i < len(pos)}
            statics |= set(entry.static_argnames or [])
            self.traced_params = {
                p for i, p in enumerate(pos)
                if p not in statics and i not in static
                and p not in ("self", "cls")}

    def _flag(self, line: int, rule: str, what: str, why: str) -> None:
        via = ""
        if len(self.chain) > 1:
            via = f" [traced via {_chain_str(self.chain)}]"
        self.out.append((
            line, rule,
            f"{what} inside jit-traced code (entry "
            f"{self.entry.fi.name}() at "
            f"{self.entry.fi.path.rsplit('/', 1)[-1]}:"
            f"{self.entry.line}){via} — {why}"))

    def run(self) -> List[tuple]:
        fn = self.fi.node
        # global/nonlocal declarations + whether declared names are
        # assigned in this function
        declared: Dict[str, int] = {}
        assigned: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, _FUNC_NODES) and n is not fn:
                continue
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                for name in n.names:
                    declared.setdefault(name, n.lineno)
            elif isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store,)):
                assigned.add(n.id)
            elif isinstance(n, ast.AugAssign) and isinstance(
                    n.target, ast.Name):
                assigned.add(n.target.id)
        for name, line in sorted(declared.items()):
            if name in assigned:
                self._flag(
                    line, "xp-jit-impure-mutation",
                    f"assignment to {name!r} declared "
                    f"global/nonlocal",
                    "the mutation happens at TRACE time only — the "
                    "compiled program never re-runs it, so state "
                    "diverges after the first call per signature")
        for n in ast.walk(fn):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self._flag(
                            n.lineno, "xp-jit-impure-mutation",
                            f"mutation of self.{t.attr}",
                            "runs at trace time only; later calls "
                            "reuse the compiled program and skip it "
                            "— carry state through function "
                            "arguments/returns instead")
            elif isinstance(n, ast.Call):
                self._call(n)
        return self.out

    def _call(self, call: ast.Call) -> None:
        f = call.func
        recv, name = _dotted_tail(f)
        if (isinstance(f, ast.Attribute)
                and f.attr in _HOST_SYNC_METHODS and not call.args):
            self._flag(
                call.lineno, "xp-jit-host-sync",
                f".{f.attr}() call",
                "forces a device->host sync; under trace it raises "
                "ConcretizationTypeError or freezes a trace-time "
                "constant into the program")
            return
        if name in _HOST_ARRAY_FUNCS and recv in _NUMPY_NAMES:
            self._flag(
                call.lineno, "xp-jit-host-sync",
                f"{recv}.{name}() materialization",
                "pulls the traced value to host numpy — use jnp "
                "inside jit and convert at the boundary")
            return
        if name == "device_get" and recv == "jax":
            self._flag(
                call.lineno, "xp-jit-host-sync",
                "jax.device_get() call",
                "explicit device->host transfer inside the trace")
            return
        if (isinstance(f, ast.Name) and f.id == "print"):
            self._flag(
                call.lineno, "xp-jit-host-sync",
                "print() of traced values",
                "prints tracer reprs once at trace time, then never "
                "again — use jax.debug.print for runtime values")
            return
        if (isinstance(f, ast.Name) and f.id in _CAST_BUILTINS
                and len(call.args) == 1
                and isinstance(call.args[0], ast.Name)
                and call.args[0].id in self.traced_params):
            self._flag(
                call.lineno, "xp-jit-host-sync",
                f"{f.id}({call.args[0].id}) on a traced parameter",
                "concretizes the tracer — ConcretizationTypeError "
                "at compile time; mark the argument static or keep "
                "the math in jnp")


def check(idx: ProjectIndex,
          graph: Optional[CallGraph] = None,
          only: Optional[Set[str]] = None) -> List:
    from ..raylint import Finding

    entries = find_entries(idx)
    graph = graph or CallGraph(idx)
    findings: List[Finding] = []
    # (path, line, rule): report each site once, for the shortest chain
    seen: Set[Tuple[str, int, str]] = set()

    for entry in entries:
        reach = graph.reachable([entry.fi.qual])
        # static_argnums sanity on the entry itself
        if only is None or entry.fi.path in only:
            findings.extend(_check_static_args(entry, idx))
        for qual, chain in sorted(reach.items()):
            fi = idx.functions.get(qual)
            if fi is None:
                continue
            # reachability stays global (a changed helper can be
            # traced from an unchanged entry); only the scan of each
            # reached body is scoped
            if only is not None and fi.path not in only:
                continue
            for line, rule, msg in _TracedScan(fi, entry, chain).run():
                key = (fi.path, line, rule)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(fi.path, line, rule, msg))

    # unhashable literals at static positions of resolved entry calls
    entry_by_qual = {e.fi.qual: e for e in entries}
    for fi in idx.all_functions():
        if only is not None and fi.path not in only:
            continue
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            callee = idx.resolve_call(n.func, fi)
            if callee is None:
                continue
            e = entry_by_qual.get(callee.qual)
            if e is None or not e.static_argnums:
                continue
            for i in e.static_argnums:
                if i < len(n.args) and isinstance(
                        n.args[i], (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp)):
                    key = (fi.path, n.lineno, "xp-jit-static-args")
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        fi.path, n.lineno, "xp-jit-static-args",
                        f"{callee.name}() marks argument {i} static "
                        f"but this call passes an unhashable "
                        f"literal there — jit raises TypeError on "
                        f"the first call; pass a hashable "
                        f"(tuple/frozen) value"))

    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


def _check_static_args(entry: JitEntry, idx: ProjectIndex) -> List:
    from ..raylint import Finding

    out: List[Finding] = []
    fi = entry.fi
    args = fi.node.args
    pos = fi.param_names()
    has_vararg = args.vararg is not None
    for i in entry.static_argnums or []:
        if i >= len(pos) and not has_vararg:
            out.append(Finding(
                fi.path, entry.line, "xp-jit-static-args",
                f"static_argnums={i} but {fi.name}() has only "
                f"{len(pos)} positional parameter(s) — IndexError "
                f"at the first call (a reordered signature left the "
                f"decorator behind?)"))
    kw = {p.arg for p in args.kwonlyargs}
    for name in entry.static_argnames or []:
        if name not in pos and name not in kw:
            out.append(Finding(
                fi.path, entry.line, "xp-jit-static-args",
                f"static_argnames={name!r} is not a parameter of "
                f"{fi.name}() — the pin silently does nothing, so "
                f"the intended-static argument retraces on every "
                f"new value"))
    return out
