"""Interprocedural dataflow substrate for the whole-program analyses.

The lock/protocol passes each grew their own ad-hoc walkers; the three
API-surface analyses (remote-call contracts, ObjectRef lifetime,
jit-purity) all need the same three capabilities, so they live here
once:

- **value resolution** (`resolve_value`): what project symbol a
  Name/Attribute chain denotes — a function, a class, a module — using
  the index's import/attr-type tables. This is `ProjectIndex
  .resolve_call`'s logic exposed for *non-call* expressions, so
  ``fn.remote(...)`` can resolve ``fn`` to the decorated def.
- **value provenance for call results** (`RemoteResolver` +
  `LocalEnv`): what a local name *holds* — an actor handle of a known
  class (bound from ``Cls.remote()``, from a call to a function whose
  returns are handle creations, from subscripting a known handle
  list, or from iterating one), a remote-wrapped alias
  (``Worker = remote(num_cpus=2)(_MapWorker)``), or a list of
  handles. Class attributes get the same treatment
  (``self.w = Worker.remote()`` in any method types ``self.w``
  everywhere in the class), and function return types are solved to a
  fixed point so ``controller = _get_or_create_controller()`` types
  ``controller`` through the helper.
- **a resolved call graph** (`CallGraph`): qual -> callee quals using
  `resolve_call` over every call site, with reachability — the
  jit-purity pass walks it from every jit entry point, and `--stats`
  reports its edge count.

Remote-call *sites* — every ``X.remote(...)`` with the resolved
target, the accumulated ``.options(...)`` keys, decorator options, and
the assignment shape at the site — are extracted here too
(`remote_sites`), because both the contract checker and the lifetime
pass consume them. Resolution is conservative by construction: an
unresolved receiver contributes *nothing* (no finding), matching the
index's err-toward-missing-an-edge philosophy.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple, Union

from .index import ClassInfo, FuncInfo, ModuleInfo, ProjectIndex

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)

Resolved = Union[FuncInfo, ClassInfo, ModuleInfo]

_RAY_MODULES = {"ray", "ray_tpu", "rt"}


def resolve_value(expr: ast.AST, scope: FuncInfo,
                  idx: ProjectIndex) -> Optional[Resolved]:
    """What project symbol `expr` (a Name / dotted Attribute) denotes
    in `scope` — function, class, or module — or None."""
    mi = scope.module
    if isinstance(expr, ast.Name):
        r = _resolve_name(expr.id, scope, idx)
        if r is not None:
            return r
        mod = idx.modules.get(mi.imports.get(expr.id, ""))
        return mod
    if isinstance(expr, ast.Attribute):
        base = resolve_value(expr.value, scope, idx)
        if isinstance(base, ModuleInfo):
            return (base.functions.get(expr.attr)
                    or base.classes.get(expr.attr)
                    or idx.modules.get(f"{base.modname}.{expr.attr}"))
        if isinstance(base, ClassInfo):
            return idx.find_method(base.qual, expr.attr)
    return None


def _resolve_name(name: str, scope: FuncInfo,
                  idx: ProjectIndex) -> Optional[Resolved]:
    fn: Optional[FuncInfo] = scope
    while fn is not None:
        if name in fn.nested:
            return fn.nested[name]
        if name in fn.nested_classes:
            return fn.nested_classes[name]
        fn = fn.parent
    mi = scope.module
    if name in mi.functions:
        return mi.functions[name]
    if name in mi.classes:
        return mi.classes[name]
    target = mi.imports.get(name)
    if target is not None:
        return (idx.functions.get(target) or idx.classes.get(target)
                or idx.modules.get(target))
    return None


# ---------------------------------------------------------------------
# Remote decorations
# ---------------------------------------------------------------------

_REMOTE_NAMES = {"remote"}


def remote_decoration(node: ast.AST) -> Optional[Dict[str, ast.expr]]:
    """None when `node` is not @remote-decorated; otherwise the
    decorator's keyword options (``@remote(num_returns=2)`` ->
    {"num_returns": <Constant 2>}, bare ``@remote`` -> {})."""
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = (target.id if isinstance(target, ast.Name)
                else target.attr if isinstance(target, ast.Attribute)
                else None)
        if name in _REMOTE_NAMES:
            if isinstance(dec, ast.Call):
                return {kw.arg: kw.value for kw in dec.keywords
                        if kw.arg is not None}
            return {}
    return None


def method_decoration(node: ast.AST) -> Dict[str, ast.expr]:
    """@method(num_returns=...) per-method defaults on an actor
    method (empty when undecorated)."""
    for dec in getattr(node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        target = dec.func
        name = (target.id if isinstance(target, ast.Name)
                else target.attr if isinstance(target, ast.Attribute)
                else None)
        if name == "method":
            return {kw.arg: kw.value for kw in dec.keywords
                    if kw.arg is not None}
    return {}


def _is_remote_callable(expr: ast.AST) -> bool:
    """``remote`` / ``ray.remote`` / ``ray_tpu.remote`` as a value —
    the decorator used in call form."""
    if isinstance(expr, ast.Name):
        return expr.id in _REMOTE_NAMES
    return (isinstance(expr, ast.Attribute)
            and expr.attr in _REMOTE_NAMES
            and isinstance(expr.value, ast.Name)
            and expr.value.id in _RAY_MODULES)


# ---------------------------------------------------------------------
# Remote-call sites
# ---------------------------------------------------------------------


@dataclass
class RemoteSite:
    """One ``X.remote(...)`` call, resolved."""
    call: ast.Call
    scope: FuncInfo
    kind: str                     # "task" | "actor_create" | "actor_method"
    target: Optional[Resolved]    # FuncInfo / ClassInfo when resolved
    # method FuncInfo for actor_method (target is then the ClassInfo)
    method: Optional[FuncInfo] = None
    method_name: Optional[str] = None
    # merged keyword options: decorator opts overlaid with every
    # .options(...) hop at the site (literal keywords only)
    options: Dict[str, ast.expr] = field(default_factory=dict)
    # .options(...) call nodes at the site, for line numbers
    option_calls: List[ast.Call] = field(default_factory=list)
    options_dynamic: bool = False   # .options(**kw) seen: keys unknown

    @property
    def line(self) -> int:
        return self.call.lineno

    def describe(self) -> str:
        if self.kind == "actor_method" and self.method_name:
            base = self.target.name if isinstance(
                self.target, ClassInfo) else "<actor>"
            return f"{base}.{self.method_name}.remote"
        if self.target is not None:
            return f"{self.target.name}.remote"
        return "<unresolved>.remote"


def _unwrap_options(expr: ast.AST) -> Tuple[ast.AST, List[ast.Call], bool]:
    """Peel ``.options(...)`` hops off a receiver chain; returns the
    base expression, the option calls outermost-first, and whether any
    hop used **kwargs (keys then unknowable)."""
    calls: List[ast.Call] = []
    dynamic = False
    while (isinstance(expr, ast.Call)
           and isinstance(expr.func, ast.Attribute)
           and expr.func.attr == "options"):
        calls.append(expr)
        if any(kw.arg is None for kw in expr.keywords) or expr.args:
            dynamic = True
        expr = expr.func.value
    return expr, calls, dynamic


@dataclass
class LocalEnv:
    """Per-function provenance: what each local name holds."""
    # name -> actor class the name is a handle on
    handles: Dict[str, ClassInfo] = field(default_factory=dict)
    # name -> actor class for lists/dicts whose VALUES are handles
    handle_lists: Dict[str, ClassInfo] = field(default_factory=dict)
    # name -> (remote-wrapped fn/cls, wrap options):
    # ``W = remote(num_cpus=2)(Worker)``
    aliases: Dict[str, Tuple[Resolved, Dict[str, ast.expr]]] = \
        field(default_factory=dict)
    # names rebound to something we can't type: they shadow any
    # interprocedural (parameter) typing until rebound to a handle
    killed: Set[str] = field(default_factory=set)

    def clear_name(self, name: str) -> None:
        self.handles.pop(name, None)
        self.handle_lists.pop(name, None)
        self.aliases.pop(name, None)
        self.killed.add(name)

    def mark(self, name: str) -> None:
        self.killed.discard(name)


class _Provenance:
    """Handle provenance that crosses function boundaries:

    - ``self.w = Worker.remote(...)`` in any method of class C types
      ``self.w`` as a Worker handle everywhere in C (attr lists of
      handles likewise);
    - a function whose return statements produce actor handles types
      every ``x = that_fn()`` call result (solved to a fixed point so
      one helper can defer to another).
    """

    def __init__(self, resolver: "RemoteResolver"):
        self.resolver = resolver
        self.idx = resolver.idx
        # class qual -> attr -> actor ClassInfo
        self.cls_attrs: Dict[str, Dict[str, ClassInfo]] = {}
        # class qual -> attr -> element class for handle containers
        self.cls_attr_lists: Dict[str, Dict[str, ClassInfo]] = {}
        # class qual -> attr -> remote-wrap alias stored on self
        # (``self._gen_cls = remote(**opts)(RolloutWorker)``)
        self.cls_attr_aliases: Dict[
            str, Dict[str, Tuple[Resolved, Dict[str, ast.expr]]]] = {}
        # function qual -> actor class its returns create
        self.fn_returns: Dict[str, ClassInfo] = {}
        # function qual -> param name -> actor class flowing in from
        # every resolved call site (None = conflicting sites: unknown)
        self.fn_params: Dict[str, Dict[str, Optional[ClassInfo]]] = {}
        # id(call) -> resolved callee (or None); resolution is
        # env-independent, and the fixed point re-resolves every call
        # each pass
        self._callee_memo: Dict[int, Optional[object]] = {}
        # the build's own scans resolve through the resolver, which
        # reads resolver.handles — install self before building
        resolver.handles = self
        self._build()

    def _fingerprint(self):
        return (
            {q: {a: c.qual for a, c in t.items()}
             for q, t in self.cls_attrs.items()},
            {q: {a: c.qual for a, c in t.items()}
             for q, t in self.cls_attr_lists.items()},
            {q: sorted(t) for q, t in self.cls_attr_aliases.items()},
            {q: c.qual for q, c in self.fn_returns.items()},
            {q: {a: (c.qual if c else None) for a, c in t.items()}
             for q, t in self.fn_params.items()},
        )

    def _build(self) -> None:
        # Fixed point: each pass sees handles discovered by the last
        # (attr alias -> helper return -> attr list -> param typing is
        # a real 4-deep chain in the rlhf pipeline), capped to bound
        # the cost on adversarial inputs.
        for _ in range(5):
            before = self._fingerprint()
            for fi in self.idx.all_functions():
                self._scan(fi)
            if self._fingerprint() == before:
                break

    def _scan(self, fi: FuncInfo) -> None:
        res = self.resolver
        # closure seeding: handles bound in enclosing functions are
        # visible (and meaningful) inside nested defs
        env = res.seed_env(fi)

        def self_attr(tgt: ast.AST) -> Optional[str]:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self" and fi.cls is not None):
                return tgt.attr
            return None

        def stmt_pass(plan: List) -> None:
            for stmt, gens, calls in plan:
                res.bind_gens(env, gens, fi)
                for call in calls:
                    self._learn_params(call, fi, env)
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    res.bind(env, stmt, fi)
                    targets = (stmt.targets
                               if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    if stmt.value is None:
                        targets = []
                    for tgt in targets:
                        attr = self_attr(tgt)
                        if attr is None:
                            continue
                        cls = res.creation_class(stmt.value, fi, env)
                        if cls is not None:
                            self.cls_attrs.setdefault(
                                fi.cls.qual, {}).setdefault(attr, cls)
                        lcls = res.handle_list_class(stmt.value,
                                                     fi, env)
                        if lcls is not None:
                            self.cls_attr_lists.setdefault(
                                fi.cls.qual, {}).setdefault(attr, lcls)
                        alias = res.remote_alias(stmt.value, fi)
                        if alias is not None:
                            self.cls_attr_aliases.setdefault(
                                fi.cls.qual, {}).setdefault(attr, alias)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    res.bind_for(env, stmt, fi)
                elif isinstance(stmt, ast.Return) and stmt.value:
                    cls = res.creation_class(stmt.value, fi, env)
                    if cls is not None:
                        self.fn_returns.setdefault(fi.qual, cls)
                elif isinstance(stmt, ast.Expr):
                    grown = res.bind_append(env, stmt.value, fi)
                    if grown is not None:
                        recv, cls = grown
                        attr = self_attr(recv)
                        if attr is not None:
                            self.cls_attr_lists.setdefault(
                                fi.cls.qual, {}).setdefault(attr, cls)

        stmt_pass(res.stmt_plan(fi))

    def _learn_params(self, call: ast.Call, fi: FuncInfo,
                      env: "LocalEnv") -> None:
        """Flow handle types from a call site into the callee's
        parameters (``self._submit(runner_handle)`` types ``runner``
        inside ``_submit``)."""
        key = id(call)
        if key in self._callee_memo:
            callee = self._callee_memo[key]
        else:
            callee = self.idx.resolve_call(call.func, fi)
            self._callee_memo[key] = callee
        if not isinstance(callee, FuncInfo):
            return
        params = callee.param_names()
        if params and params[0] in ("self", "cls") \
                and callee.cls is not None:
            params = params[1:]
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break           # positions beyond a * are unknown
            if i >= len(params):
                break
            cls = self.resolver.creation_class(arg, fi, env)
            if cls is not None:
                self._record_param(callee.qual, params[i], cls)
        names = set(params)
        names.update(a.arg for a in callee.node.args.kwonlyargs)
        for kw in call.keywords:
            if kw.arg is None or kw.arg not in names:
                continue
            cls = self.resolver.creation_class(kw.value, fi, env)
            if cls is not None:
                self._record_param(callee.qual, kw.arg, cls)

    def _record_param(self, qual: str, name: str,
                      cls: ClassInfo) -> None:
        tbl = self.fn_params.setdefault(qual, {})
        if name not in tbl:
            tbl[name] = cls
        elif tbl[name] is not None and tbl[name].qual != cls.qual:
            tbl[name] = None    # conflicting call sites: poison

    def param_actor(self, fn_qual: str,
                    name: str) -> Optional[ClassInfo]:
        return self.fn_params.get(fn_qual, {}).get(name)

    def _bfs_attr(self, table: Dict[str, Dict[str, ClassInfo]],
                  cls_qual: str, attr: str) -> Optional[ClassInfo]:
        seen: Set[str] = set()
        queue = [cls_qual]
        while queue:
            q = queue.pop(0)
            if q in seen:
                continue
            seen.add(q)
            got = table.get(q, {}).get(attr)
            if got is not None:
                return got
            ci = self.idx.classes.get(q)
            if ci is not None:
                queue.extend(ci.base_quals)
        return None

    def attr_actor(self, cls_qual: str,
                   attr: str) -> Optional[ClassInfo]:
        return self._bfs_attr(self.cls_attrs, cls_qual, attr)

    def attr_actor_list(self, cls_qual: str,
                        attr: str) -> Optional[ClassInfo]:
        return self._bfs_attr(self.cls_attr_lists, cls_qual, attr)

    def attr_alias(self, cls_qual: str, attr: str,
                   ) -> Optional[Tuple[Resolved, Dict[str, ast.expr]]]:
        return self._bfs_attr(self.cls_attr_aliases, cls_qual, attr)


class RemoteResolver:
    """Resolves ``.remote(...)`` receivers through the index plus
    dataflow provenance (local handle bindings, remote-wrap aliases,
    handle-returning helpers, handle containers)."""

    def __init__(self, idx: ProjectIndex):
        self.idx = idx
        # memo tables keyed by id(node) — AST nodes live as long as the
        # index, so ids are stable. The provenance fixed point and the
        # per-analysis scans re-walk the same expressions several
        # times; caching the walk products (not the bindings, which
        # depend on the env) makes passes 2..n nearly free.
        self._comp_memo: Dict[int, List[ast.comprehension]] = {}
        self._call_memo: Dict[int, List[ast.Call]] = {}
        # function qual -> pre-order (stmt, [expr children]) plan; the
        # provenance fixed point revisits every function each pass and
        # the traversal itself is most of a pass's cost
        self._plan_memo: Dict[str, List] = {}
        self.handles = _Provenance(self)

    def stmt_plan(self, fi: FuncInfo) -> List:
        """Pre-order (stmt, comprehension generators, calls) triples
        for the function body — the walk products the provenance fixed
        point needs, computed once so repeat passes traverse no AST."""
        plan = self._plan_memo.get(fi.qual)
        if plan is None:
            plan = []

            def flatten(stmts: List[ast.stmt]) -> None:
                for stmt in stmts:
                    if isinstance(stmt, _SKIP_NODES):
                        continue
                    gens: List[ast.comprehension] = []
                    calls: List[ast.Call] = []
                    for c in ast.iter_child_nodes(stmt):
                        if isinstance(c, (ast.stmt, ast.excepthandler)):
                            continue
                        # one fused walk per expr child; within an
                        # expression there are no def/class nodes to
                        # prune, so this matches _iter_calls exactly
                        for node in ast.walk(c):
                            if isinstance(node, ast.Call):
                                calls.append(node)
                            elif isinstance(node, _COMP_NODES):
                                gens.extend(node.generators)
                    plan.append((stmt, gens, calls))
                    for body in _stmt_bodies(stmt):
                        flatten(body)

            flatten(list(getattr(fi.node, "body", [])))
            self._plan_memo[fi.qual] = plan
        return plan

    def comp_gens(self, expr: ast.AST) -> List[ast.comprehension]:
        gens = self._comp_memo.get(id(expr))
        if gens is None:
            gens = [gen for node in ast.walk(expr)
                    if isinstance(node, (ast.ListComp, ast.SetComp,
                                         ast.GeneratorExp, ast.DictComp))
                    for gen in node.generators]
            self._comp_memo[id(expr)] = gens
        return gens

    def calls_in(self, node: ast.AST) -> List[ast.Call]:
        got = self._call_memo.get(id(node))
        if got is None:
            got = list(_iter_calls(node))
            self._call_memo[id(node)] = got
        return got

    # -- site resolution ---------------------------------------------

    def site(self, call: ast.Call, scope: FuncInfo,
             env: Optional[LocalEnv] = None) -> Optional[RemoteSite]:
        """A RemoteSite when `call` is ``X.remote(...)``, else None.
        `env` carries the caller's statement-walk provenance."""
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "remote"):
            return None
        base, opt_calls, dynamic = _unwrap_options(f.value)
        options: Dict[str, ast.expr] = {}
        for oc in reversed(opt_calls):
            for kw in oc.keywords:
                if kw.arg is not None:
                    options[kw.arg] = kw.value

        def finish(kind: str, target: Optional[Resolved],
                   method: Optional[FuncInfo] = None,
                   method_name: Optional[str] = None,
                   deco_opts: Optional[Dict[str, ast.expr]] = None,
                   ) -> RemoteSite:
            merged = dict(deco_opts or {})
            merged.update(options)
            return RemoteSite(call, scope, kind, target, method,
                              method_name, merged, opt_calls, dynamic)

        # remote-wrap alias: W = remote(...)(Worker); W.remote(...)
        if (env is not None and isinstance(base, ast.Name)
                and base.id in env.aliases):
            target, deco = env.aliases[base.id]
            kind = ("actor_create" if isinstance(target, ClassInfo)
                    else "task")
            return finish(kind, target, deco_opts=deco)

        # inline wrap: remote(num_cpus=1)(fn).remote(...)
        wrapped = self.remote_alias(base, scope)
        if wrapped is not None:
            target, deco = wrapped
            kind = ("actor_create" if isinstance(target, ClassInfo)
                    else "task")
            return finish(kind, target, deco_opts=deco)

        # direct: fn.remote / Cls.remote / mod.fn.remote
        r = resolve_value(base, scope, self.idx)
        if r is not None and not isinstance(r, ModuleInfo):
            deco = remote_decoration(r.node)
            if deco is None:
                return None       # .remote on a non-@remote symbol:
                                  # contracts.py reports separately
            if isinstance(r, ClassInfo):
                return finish("actor_create", r, deco_opts=deco)
            return finish("task", r, deco_opts=deco)

        # remote-wrap alias stored on self:
        # self._cls = remote(**opts)(Worker); self._cls.remote(...)
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and scope.cls is not None):
            al = self.handles.attr_alias(scope.cls.qual, base.attr)
            if al is not None:
                target, deco = al
                kind = ("actor_create" if isinstance(target, ClassInfo)
                        else "task")
                return finish(kind, target, deco_opts=deco)

        # actor method: handle.meth.remote
        if isinstance(base, ast.Attribute):
            actor = self._handle_of(base.value, scope, env)
            if actor is not None:
                m = self.idx.find_method(actor.qual, base.attr)
                deco = method_decoration(m.node) if m else {}
                return finish("actor_method", actor, m, base.attr,
                              deco_opts=deco)
        return None

    def _handle_of(self, expr: ast.AST, scope: FuncInfo,
                   env: Optional[LocalEnv]) -> Optional[ClassInfo]:
        """The actor class `expr` is a handle on, or None."""
        if isinstance(expr, ast.Name):
            if env and expr.id in env.handles:
                return env.handles[expr.id]
            # a parameter typed by its (consistent) call sites —
            # unless a local rebinding shadowed it
            if env is None or expr.id not in env.killed:
                return self.handles.param_actor(scope.qual, expr.id)
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and scope.cls is not None):
            return self.handles.attr_actor(scope.cls.qual, expr.attr)
        if isinstance(expr, ast.Subscript):
            return self._list_elem_of(expr.value, scope, env)
        return None

    def _list_elem_of(self, expr: ast.AST, scope: FuncInfo,
                      env: Optional[LocalEnv]) -> Optional[ClassInfo]:
        """Element class when `expr` is a known handle container."""
        if isinstance(expr, ast.Name):
            if env and expr.id in env.handle_lists:
                return env.handle_lists[expr.id]
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and scope.cls is not None):
            return self.handles.attr_actor_list(scope.cls.qual,
                                                expr.attr)
        return None

    # -- provenance classification -----------------------------------

    def remote_alias(self, value: ast.AST, scope: FuncInfo,
                     ) -> Optional[Tuple[Resolved,
                                         Dict[str, ast.expr]]]:
        """(wrapped target, wrap options) for the call-form decorator:
        ``remote(fn)`` or ``remote(num_cpus=2)(fn)``."""
        if not isinstance(value, ast.Call):
            return None
        opts: Dict[str, ast.expr] = {}
        if (isinstance(value.func, ast.Call)
                and _is_remote_callable(value.func.func)):
            opts = {kw.arg: kw.value for kw in value.func.keywords
                    if kw.arg is not None}
            target_expr = value.args[0] if value.args else None
        elif _is_remote_callable(value.func):
            target_expr = value.args[0] if value.args else None
            if target_expr is None or value.keywords:
                # remote(num_cpus=2) alone is a partial decorator,
                # not a wrapped callable
                return None
        else:
            return None
        if target_expr is None:
            return None
        r = resolve_value(target_expr, scope, self.idx)
        if isinstance(r, (FuncInfo, ClassInfo)):
            return r, opts
        return None

    def creation_class(self, value: ast.AST, scope: FuncInfo,
                       env: LocalEnv) -> Optional[ClassInfo]:
        """The actor class when `value` evaluates to a handle."""
        if isinstance(value, ast.Call):
            if (isinstance(value.func, ast.Attribute)
                    and value.func.attr == "remote"):
                s = self.site(value, scope, env)
                if (s is not None and s.kind == "actor_create"
                        and isinstance(s.target, ClassInfo)):
                    return s.target
                return None
            callee = self.idx.resolve_call(value.func, scope)
            if callee is not None:
                return self.handles.fn_returns.get(callee.qual)
            return None
        if isinstance(value, ast.Subscript):
            return self._list_elem_of(value.value, scope, env)
        if isinstance(value, (ast.Name, ast.Attribute)):
            return self._handle_of(value, scope, env)
        return None

    def handle_list_class(self, value: ast.AST, scope: FuncInfo,
                          env: LocalEnv) -> Optional[ClassInfo]:
        """Element class when `value` builds a container of handles."""
        if isinstance(value, (ast.ListComp, ast.SetComp)):
            return self.creation_class(value.elt, scope, env)
        if isinstance(value, ast.DictComp):
            return self.creation_class(value.value, scope, env)
        if isinstance(value, (ast.List, ast.Set, ast.Tuple)):
            classes = [self.creation_class(e, scope, env)
                       for e in value.elts]
            if classes and all(c is not None for c in classes) \
                    and len({c.qual for c in classes}) == 1:
                return classes[0]
            return None
        if isinstance(value, (ast.Name, ast.Attribute)):
            return self._list_elem_of(value, scope, env)
        return None

    # -- statement-walk binding helpers ------------------------------

    def bind(self, env: LocalEnv, stmt: ast.stmt,
             scope: FuncInfo) -> None:
        """Update `env` for one (possibly annotated) assignment."""
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                self._bind_one(env, stmt.target.id, stmt.value, scope)
            return
        v = stmt.value
        for tgt in stmt.targets:
            if (isinstance(tgt, ast.Tuple) and isinstance(v, ast.Tuple)
                    and len(tgt.elts) == len(v.elts)
                    and not any(isinstance(e, ast.Starred)
                                for e in tgt.elts)):
                # s1, s2 = Stage.remote(), Stage.remote()
                for t_i, v_i in zip(tgt.elts, v.elts):
                    if isinstance(t_i, ast.Name):
                        self._bind_one(env, t_i.id, v_i, scope)
            elif isinstance(tgt, ast.Name):
                self._bind_one(env, tgt.id, v, scope)

    def _bind_one(self, env: LocalEnv, name: str, value: ast.AST,
                  scope: FuncInfo) -> None:
        alias = self.remote_alias(value, scope)
        cls = self.creation_class(value, scope, env)
        lcls = self.handle_list_class(value, scope, env)
        env.clear_name(name)
        if alias is not None:
            env.aliases[name] = alias
        elif cls is not None:
            env.handles[name] = cls
        elif lcls is not None:
            env.handle_lists[name] = lcls
        else:
            return          # unknown value: name stays killed
        env.mark(name)

    def _unwrap_iter(self, it: ast.AST,
                     tgt: Optional[ast.AST]) -> Tuple[ast.AST,
                                                      Optional[ast.AST]]:
        """Peel transparent wrappers off a loop iterable:
        ``enumerate(xs)`` (shifting a 2-tuple target), ``list/sorted/
        tuple/reversed(xs)``, ``xs.values()``."""
        for _ in range(3):
            if (isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name) and it.args):
                if it.func.id == "enumerate":
                    it = it.args[0]
                    if (isinstance(tgt, ast.Tuple)
                            and len(tgt.elts) == 2):
                        tgt = tgt.elts[1]
                    continue
                if (it.func.id in ("list", "sorted", "tuple",
                                   "reversed")
                        and len(it.args) == 1):
                    it = it.args[0]
                    continue
            if (isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Attribute)
                    and it.func.attr == "values" and not it.args):
                it = it.func.value
                continue
            break
        return it, tgt

    def bind_for(self, env: LocalEnv, stmt: ast.stmt,
                 scope: FuncInfo) -> None:
        """Type the loop variable when iterating a handle container
        (``for r in self.runners:`` / ``enumerate(actors)``)."""
        it, tgt = self._unwrap_iter(stmt.iter, stmt.target)
        cls = self._list_elem_of(it, scope, env)
        if cls is None:
            return
        if isinstance(tgt, ast.Name):
            env.clear_name(tgt.id)
            env.handles[tgt.id] = cls
            env.mark(tgt.id)

    def bind_comps(self, env: LocalEnv, expr: ast.AST,
                   scope: FuncInfo) -> None:
        """Type comprehension loop variables ranging over handle
        containers (``[r.sample.remote(...) for r in self.runners]``).
        Comprehension scoping is ignored — the binding outlives the
        expression — but only typed names are recorded, so the
        over-approximation can only resolve more receivers."""
        self.bind_gens(env, self.comp_gens(expr), scope)

    def bind_gens(self, env: LocalEnv,
                  gens: List[ast.comprehension],
                  scope: FuncInfo) -> None:
        for gen in gens:
            it, tgt = self._unwrap_iter(gen.iter, gen.target)
            cls = self._list_elem_of(it, scope, env)
            if cls is not None and isinstance(tgt, ast.Name):
                env.handles[tgt.id] = cls
                env.mark(tgt.id)

    def bind_append(self, env: LocalEnv, value: ast.AST,
                    scope: FuncInfo,
                    ) -> Optional[Tuple[ast.AST, ClassInfo]]:
        """``xs.append(Worker.remote(...))`` grows a handle container;
        returns (receiver expr, element class) so the provenance pass
        can record ``self.X.append(...)`` too."""
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in ("append", "add", "insert")
                and value.args):
            return None
        cls = self.creation_class(value.args[-1], scope, env)
        if cls is None:
            return None
        recv = value.func.value
        if isinstance(recv, ast.Name):
            env.handle_lists.setdefault(recv.id, cls)
            env.mark(recv.id)
        return recv, cls

    def bind_stmt(self, env: LocalEnv, stmt: ast.stmt,
                  scope: FuncInfo) -> None:
        """One-stop binding for a statement-ordered walk."""
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, (ast.stmt, ast.excepthandler)):
                self.bind_comps(env, child, scope)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            self.bind(env, stmt, scope)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.bind_for(env, stmt, scope)
        elif isinstance(stmt, ast.Expr):
            self.bind_append(env, stmt.value, scope)

    def seed_env(self, scope: FuncInfo) -> LocalEnv:
        """A LocalEnv pre-seeded with bindings from enclosing
        functions — nested defs close over the parent's handles
        (``actor = Pong.remote()`` above, ``actor.ping.remote()``
        inside the nested benchmark body). Late rebinding is ignored;
        only typed names carry over, so the cost of the
        over-approximation is an extra resolved receiver, never a
        missed one."""
        if scope.parent is None:
            return LocalEnv()
        chain: List[FuncInfo] = []
        fn = scope.parent
        while fn is not None:
            chain.append(fn)
            fn = fn.parent
        env = LocalEnv()
        for anc in reversed(chain):
            self._bind_pass(env, list(getattr(anc.node, "body", [])),
                            anc)
        return env

    def _bind_pass(self, env: LocalEnv, stmts: List[ast.stmt],
                   scope: FuncInfo) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            self.bind_stmt(env, stmt, scope)
            for body in _stmt_bodies(stmt):
                self._bind_pass(env, body, scope)


def _iter_calls(node: ast.AST) -> Iterable[ast.Call]:
    """Calls in `node`, not descending into nested defs/classes
    (lambdas and comprehensions stay — they share the dataflow)."""
    stack = list(ast.iter_child_nodes(node))
    if isinstance(node, ast.Call):
        yield node
    while stack:
        n = stack.pop()
        if isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def remote_sites(idx: ProjectIndex,
                 resolver: Optional[RemoteResolver] = None,
                 only: Optional[Set[str]] = None) -> List[RemoteSite]:
    """Every resolved ``.remote(...)`` site in the project, with
    function-local provenance applied (``w = Worker.remote();
    w.ping.remote()``). `only` restricts the scan to functions in
    those files (resolution stays whole-program)."""
    resolver = resolver or RemoteResolver(idx)
    out: List[RemoteSite] = []
    for fi in idx.all_functions():
        if only is not None and fi.path not in only:
            continue
        out.extend(_sites_in(fi, resolver, idx))
    return out


def _sites_in(fi: FuncInfo, resolver: RemoteResolver,
              idx: ProjectIndex) -> List[RemoteSite]:
    out: List[RemoteSite] = []
    env = resolver.seed_env(fi)
    seen: Set[ast.Call] = set()

    def visit_expr(expr: ast.AST) -> None:
        resolver.bind_comps(env, expr, fi)
        for call in _iter_calls(expr):
            if call in seen:
                continue
            seen.add(call)
            site = resolver.site(call, fi, env)
            if site is not None:
                out.append(site)

    def walk(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            for child in ast.iter_child_nodes(stmt):
                # statements and except-handlers are walked in order
                # below — visiting them here would scan their bodies
                # before the bindings they depend on exist
                if not isinstance(child, (ast.stmt, ast.excepthandler)):
                    visit_expr(child)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                resolver.bind(env, stmt, fi)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                resolver.bind_for(env, stmt, fi)
            elif isinstance(stmt, ast.Expr):
                resolver.bind_append(env, stmt.value, fi)
            for body in _stmt_bodies(stmt):
                walk(body)

    walk(list(getattr(fi.node, "body", [])))
    return out


def _stmt_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    out = []
    for attr in ("body", "orelse", "finalbody"):
        b = getattr(stmt, attr, None)
        if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
            out.append(b)
    for h in getattr(stmt, "handlers", []):
        out.append(h.body)
    return out


# ---------------------------------------------------------------------
# Call graph + reachability
# ---------------------------------------------------------------------


class CallGraph:
    """qual -> resolved callee quals, built once per index."""

    def __init__(self, idx: ProjectIndex):
        self.idx = idx
        self.edges: Dict[str, Set[str]] = {}
        # (caller, callee) -> first call line
        self.lines: Dict[Tuple[str, str], int] = {}
        for fi in idx.all_functions():
            outs = self.edges.setdefault(fi.qual, set())
            for n in ast.walk(fi.node):
                if isinstance(n, _FUNC_NODES) and n is not fi.node:
                    continue
                if not isinstance(n, ast.Call):
                    continue
                callee = idx.resolve_call(n.func, fi)
                if callee is None:
                    continue
                outs.add(callee.qual)
                self.lines.setdefault((fi.qual, callee.qual),
                                      getattr(n, "lineno", 0))

    @property
    def edge_count(self) -> int:
        return sum(len(v) for v in self.edges.values())

    def reachable(self, roots: Iterable[str]) -> Dict[str, List[str]]:
        """qual -> call chain (root first) for everything reachable
        from `roots` (roots included, chain [root])."""
        out: Dict[str, List[str]] = {}
        queue: List[Tuple[str, List[str]]] = [
            (r, [r]) for r in roots]
        while queue:
            q, chain = queue.pop(0)
            if q in out:
                continue
            out[q] = chain
            for callee in sorted(self.edges.get(q, ())):
                if callee not in out:
                    queue.append((callee, chain + [callee]))
        return out


# ---------------------------------------------------------------------
# Signature model (for the contract checker)
# ---------------------------------------------------------------------


@dataclass
class Signature:
    name: str
    pos_params: List[str]          # posonly + regular, self stripped
    n_required_pos: int            # without defaults
    kwonly: List[str]
    kwonly_required: List[str]
    has_vararg: bool
    has_kwarg: bool
    posonly_count: int

    @classmethod
    def of(cls, fi: FuncInfo, *, strip_self: bool) -> "Signature":
        a = fi.node.args
        posonly = [p.arg for p in a.posonlyargs]
        regular = [p.arg for p in a.args]
        pos = posonly + regular
        n_defaults = len(a.defaults)
        n_required = len(pos) - n_defaults
        posonly_count = len(posonly)
        if strip_self and pos and pos[0] in ("self", "cls"):
            pos = pos[1:]
            n_required = max(0, n_required - 1)
            posonly_count = max(0, posonly_count - 1)
        kwonly = [p.arg for p in a.kwonlyargs]
        kwonly_required = [p.arg for p, d in
                           zip(a.kwonlyargs, a.kw_defaults)
                           if d is None]
        return cls(fi.name, pos, n_required, kwonly, kwonly_required,
                   a.vararg is not None, a.kwarg is not None,
                   posonly_count)

    def check_call(self, call: ast.Call) -> List[str]:
        """Human-readable contract violations for `call`'s args."""
        problems: List[str] = []
        n_pos = 0
        has_star = False
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                has_star = True
            else:
                n_pos += 1
        kw_names: List[str] = []
        has_kwstar = False
        for kw in call.keywords:
            if kw.arg is None:
                has_kwstar = True
            else:
                kw_names.append(kw.arg)
        if (not has_star and not self.has_vararg
                and n_pos > len(self.pos_params)):
            problems.append(
                f"takes at most {len(self.pos_params)} positional "
                f"argument(s) but {n_pos} are passed")
        if not self.has_kwarg:
            named = set(self.pos_params[self.posonly_count:]) | set(
                self.kwonly)
            for k in kw_names:
                if k not in named:
                    problems.append(f"got an unexpected keyword "
                                    f"argument {k!r}")
        if not has_star and not has_kwstar:
            covered = set(self.pos_params[:n_pos]) | set(kw_names)
            missing = [p for p in self.pos_params[:self.n_required_pos]
                       if p not in covered]
            missing += [p for p in self.kwonly_required
                        if p not in kw_names]
            if missing:
                problems.append(
                    "missing required argument(s): "
                    + ", ".join(repr(m) for m in missing))
            dup = [k for k in kw_names
                   if k in set(self.pos_params[:n_pos])]
            if dup:
                problems.append(
                    "got multiple values for argument(s): "
                    + ", ".join(repr(d) for d in dup))
        return problems
