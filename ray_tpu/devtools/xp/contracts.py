"""Remote-call contract checking.

A ``.remote(...)`` call crosses a process boundary, so Python's own
TypeError for a bad call fires *inside the worker*, seconds later and
in another traceback — or never, when the submission path validates
lazily. This pass resolves every ``fn.remote(...)`` /
``Cls.remote(...)`` / ``handle.method.remote(...)`` site through the
project index (plus local/attribute actor-handle provenance from the
dataflow engine) and checks three contracts at the call site:

- **signature** (``xp-remote-signature``): arity, unknown keyword
  arguments, missing required arguments, duplicate coverage — against
  the *decorated* def (``self`` stripped for actor methods and
  ``__init__``). A call through an actor handle to a method the class
  does not define is reported too (the signature-drift class: a method
  renamed while a caller kept the old name).
- **options** (``xp-remote-options``): every ``.options(...)`` key
  and ``@remote(...)`` decorator key validated against the runtime's
  *real* option tables (``core.task._VALID`` / ``_TASK_ONLY`` /
  ``_ACTOR_ONLY`` — imported, not copied, so the rule can never drift
  from the implementation). Task-only options on actors (and vice
  versa) are the same errors ``validate_options`` would raise at
  runtime. Actor-method ``.options`` accept only what
  ``submit_actor_task`` reads: ``num_returns`` / ``concurrency_group``
  / ``name``.
- **num_returns vs unpack** (``xp-remote-num-returns``): a tuple
  unpack of a ``.remote()`` result must match the declared
  ``num_returns`` (call-site ``.options`` beats ``@method``/decorator
  defaults, default 1). ``a, b = f.remote()`` with one return raises
  TypeError only when the ref is iterated — at use time, far from the
  bug.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .dataflow import (ClassInfo, FuncInfo, RemoteResolver,
                       RemoteSite, Signature, _stmt_bodies,
                       remote_sites)
from .index import ProjectIndex

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SKIP_NODES = _FUNC_NODES + (ast.ClassDef, ast.Lambda)

# Keys submit_actor_task actually reads from ActorMethod options.
_ACTOR_METHOD_OPTS = {"num_returns", "concurrency_group", "name"}


def _option_tables():
    from ...core.task import _ACTOR_ONLY, _TASK_ONLY, _VALID
    return _VALID, _TASK_ONLY, _ACTOR_ONLY


def _target_signature(site: RemoteSite,
                      idx: ProjectIndex) -> Optional[Signature]:
    if site.kind == "task" and isinstance(site.target, FuncInfo):
        return Signature.of(site.target, strip_self=False)
    if site.kind == "actor_create" and isinstance(site.target,
                                                  ClassInfo):
        init = idx.find_method(site.target.qual, "__init__")
        if init is None:
            # default __init__: zero args beyond self
            if site.call.args or any(k.arg for k in
                                     site.call.keywords):
                return Signature("__init__", [], 0, [], [], False,
                                 False, 0)
            return None
        return Signature.of(init, strip_self=True)
    if site.kind == "actor_method" and site.method is not None:
        return Signature.of(site.method, strip_self=True)
    return None


def _has_getattr(idx: ProjectIndex, cls: ClassInfo) -> bool:
    return idx.find_method(cls.qual, "__getattr__") is not None


def _num_returns_of(site: RemoteSite) -> Optional[object]:
    nr = site.options.get("num_returns")
    if nr is None:
        return 1
    if isinstance(nr, ast.Constant):
        return nr.value
    return None      # dynamic expression: unknown


def check(idx: ProjectIndex, resolver: Optional[RemoteResolver] = None,
          only: Optional[set] = None) -> List:
    from ..raylint import Finding

    valid, task_only, actor_only = _option_tables()
    resolver = resolver or RemoteResolver(idx)
    findings: List[Finding] = []
    sites = remote_sites(idx, resolver, only=only)

    for site in sites:
        path = site.scope.path

        # -- signature ------------------------------------------------
        if (site.kind == "actor_method" and site.method is None
                and isinstance(site.target, ClassInfo)
                and not _has_getattr(idx, site.target)):
            findings.append(Finding(
                path, site.line, "xp-remote-signature",
                f"{site.target.name}.{site.method_name}.remote(): "
                f"class {site.target.name} defines no method "
                f"{site.method_name!r} — the call fails inside the "
                f"worker with AttributeError (a renamed method left "
                f"this caller behind?)"))
        sig = _target_signature(site, idx)
        if sig is not None:
            for problem in sig.check_call(site.call):
                findings.append(Finding(
                    path, site.line, "xp-remote-signature",
                    f"{site.describe()}(): {problem} — the TypeError "
                    f"surfaces in the worker, not at this call site"))

        # -- options --------------------------------------------------
        is_actor = site.kind == "actor_create"
        opt_line = (site.option_calls[0].lineno
                    if site.option_calls else site.line)
        for key in sorted(site.options):
            if site.kind == "actor_method":
                if key not in _ACTOR_METHOD_OPTS:
                    findings.append(Finding(
                        path, opt_line, "xp-remote-options",
                        f"{site.describe()}: actor-method "
                        f".options() key {key!r} is ignored by "
                        f"submit_actor_task — supported: "
                        f"{sorted(_ACTOR_METHOD_OPTS)}"))
                continue
            if key not in valid:
                findings.append(Finding(
                    path, opt_line, "xp-remote-options",
                    f"{site.describe()}: unknown option {key!r} — "
                    f"validate_options raises ValueError at "
                    f"submission (valid: see core.task._VALID)"))
            elif is_actor and key in task_only:
                findings.append(Finding(
                    path, opt_line, "xp-remote-options",
                    f"{site.describe()}: option {key!r} is task-only "
                    f"but this is an actor creation — "
                    f"validate_options raises ValueError"))
            elif not is_actor and key in actor_only:
                findings.append(Finding(
                    path, opt_line, "xp-remote-options",
                    f"{site.describe()}: option {key!r} is actor-only "
                    f"but this is a task submission — "
                    f"validate_options raises ValueError"))

    # -- num_returns vs tuple unpack ---------------------------------
    for fi in idx.all_functions():
        if only is not None and fi.path not in only:
            continue
        findings.extend(_check_unpacks(fi, resolver, idx))

    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


def _check_unpacks(fi: FuncInfo, resolver: RemoteResolver,
                   idx: ProjectIndex) -> List:
    from ..raylint import Finding

    out: List[Finding] = []
    env = resolver.seed_env(fi)

    def handle_assign(stmt: ast.Assign) -> None:
        v = stmt.value
        if not (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "remote"):
            return
        site = resolver.site(v, fi, env)
        if site is None or site.kind == "actor_create":
            return
        nr = _num_returns_of(site)
        for tgt in stmt.targets:
            if not isinstance(tgt, (ast.Tuple, ast.List)):
                continue
            if any(isinstance(e, ast.Starred) for e in tgt.elts):
                continue
            n_want = len(tgt.elts)
            if nr in ("streaming", "dynamic") or nr is None:
                continue
            if nr == 1:
                out.append(Finding(
                    fi.path, stmt.lineno, "xp-remote-num-returns",
                    f"{site.describe()}() returns ONE ObjectRef "
                    f"(num_returns=1) but the result is unpacked "
                    f"into {n_want} names — declare "
                    f".options(num_returns={n_want}) or take the "
                    f"single ref"))
            elif isinstance(nr, int) and nr != n_want:
                out.append(Finding(
                    fi.path, stmt.lineno, "xp-remote-num-returns",
                    f"{site.describe()}() declares num_returns={nr} "
                    f"but the result is unpacked into {n_want} "
                    f"names — the unpack raises at use time"))

    def walk(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, _SKIP_NODES):
                continue
            if isinstance(stmt, ast.Assign):
                handle_assign(stmt)
            resolver.bind_stmt(env, stmt, fi)
            for body in _stmt_bodies(stmt):
                walk(body)

    walk(list(getattr(fi.node, "body", [])))
    return out
