"""Project-wide index: modules, symbols, imports, attribute types,
and a call-graph resolver.

Everything downstream (cross-file lock-order propagation, protocol
conformance) runs on this. Resolution is deliberately conservative —
an unresolved call contributes nothing rather than guessing — so the
whole-program rules err toward missing an edge over inventing one.

What the resolver can follow:

- bare names: nested defs of the enclosing function(s), then module
  functions/classes, then ``from x import name`` targets;
- ``self.meth()`` / ``cls.meth()``: the owning class, then its
  project base classes;
- ``mod.fn()`` / ``mod.sub.fn()``: imported project modules;
- ``self.attr.meth()`` and module-level ``INSTANCE.meth()``: attr
  types inferred from ``self.attr = ClassName(...)`` constructor
  assignments (the ``self.pool = WorkerPool(...)`` pattern);
- constructor calls resolve to the class's ``__init__``.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _child_stmts(node: ast.AST):
    """Direct statement children (plus except/match arms, which carry
    their own bodies). Defs, imports, and assigns only ever live in
    statement lists, so indexing passes need not descend into
    expression subtrees — that's most of an AST by node count."""
    for fld in ("body", "orelse", "finalbody"):
        yield from getattr(node, fld, ())
    yield from getattr(node, "handlers", ())
    yield from getattr(node, "cases", ())


def _walk_stmts(root: ast.AST):
    stack = [root]
    while stack:
        node = stack.pop()
        for child in _child_stmts(node):
            yield child
            stack.append(child)


@dataclass
class FuncInfo:
    qual: str
    module: "ModuleInfo"
    node: ast.AST
    cls: Optional["ClassInfo"] = None
    parent: Optional["FuncInfo"] = None
    nested: Dict[str, "FuncInfo"] = field(default_factory=dict)
    # classes DEFINED inside this function body (benchmark/fixture
    # style: ``@remote\nclass Pong`` inside a driver function)
    nested_classes: Dict[str, "ClassInfo"] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def path(self) -> str:
        return self.module.path

    def param_names(self) -> List[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs]
                + [p.arg for p in a.args])


@dataclass
class ClassInfo:
    qual: str
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    base_exprs: List[ast.expr] = field(default_factory=list)
    base_quals: List[str] = field(default_factory=list)
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    # self.X = ClassName(...) -> class qual
    attr_types: Dict[str, str] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    modname: str
    path: str
    tree: ast.Module
    is_pkg: bool = False
    imports: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    # module-level NAME = ClassName(...) -> class qual
    attr_types: Dict[str, str] = field(default_factory=dict)


class ProjectIndex:
    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.errors: List[Tuple[str, int, str]] = []

    # -- construction -------------------------------------------------

    @classmethod
    def build(cls, root: str) -> "ProjectIndex":
        idx = cls()
        root = os.path.abspath(root)
        pkg = os.path.basename(root.rstrip(os.sep))
        for dirpath, dirs, names in os.walk(root):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, root)
                parts = rel[:-3].split(os.sep)
                is_pkg = parts[-1] == "__init__"
                if is_pkg:
                    parts = parts[:-1]
                modname = ".".join([pkg] + parts) if parts else pkg
                idx._load_module(modname, path, is_pkg)
        for mi in idx.modules.values():
            idx._index_symbols(mi)
        for mi in idx.modules.values():
            idx._index_imports(mi)
        for mi in idx.modules.values():
            idx._index_types(mi)
        return idx

    def _load_module(self, modname: str, path: str,
                     is_pkg: bool) -> None:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            self.errors.append(
                (path, getattr(e, "lineno", 0) or 0, str(e)))
            return
        self.modules[modname] = ModuleInfo(modname, path, tree,
                                           is_pkg=is_pkg)

    def _index_symbols(self, mi: ModuleInfo) -> None:
        def walk(node: ast.AST, cls: Optional[ClassInfo],
                 parent: Optional[FuncInfo], prefix: str) -> None:
            for child in _child_stmts(node):
                if isinstance(child, ast.ClassDef):
                    qual = f"{prefix}.{child.name}"
                    ci = ClassInfo(qual, child.name, mi, child,
                                   base_exprs=list(child.bases))
                    self.classes[qual] = ci
                    if parent is not None:
                        parent.nested_classes[child.name] = ci
                    elif cls is None:
                        mi.classes[child.name] = ci
                    walk(child, ci, None, qual)
                elif isinstance(child, _FUNC_NODES):
                    qual = f"{prefix}.{child.name}"
                    fi = FuncInfo(qual, mi, child, cls=cls,
                                  parent=parent)
                    self.functions[qual] = fi
                    if parent is not None:
                        parent.nested[child.name] = fi
                    elif cls is not None:
                        cls.methods[child.name] = fi
                    else:
                        mi.functions[child.name] = fi
                    # `self` keeps meaning the method's class inside
                    # closures, so cls flows into nested defs too
                    walk(child, cls, fi, qual)
                else:
                    walk(child, cls, parent, prefix)

        walk(mi.tree, None, None, mi.modname)

    def _index_imports(self, mi: ModuleInfo) -> None:
        parts = mi.modname.split(".")
        for node in _walk_stmts(mi.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    mi.imports[local] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    drop = (node.level - 1 if mi.is_pkg
                            else node.level)
                    if drop > len(parts):
                        continue
                    base = parts[:len(parts) - drop]
                else:
                    base = []
                mod = node.module.split(".") if node.module else []
                prefix = ".".join(base + mod)
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    mi.imports[local] = (f"{prefix}.{a.name}"
                                         if prefix else a.name)

    def _index_types(self, mi: ModuleInfo) -> None:
        # base classes first, then constructor-assignment attr types
        for ci in mi.classes.values():
            for b in ci.base_exprs:
                q = self._resolve_class_expr(b, mi)
                if q is not None:
                    ci.base_quals.append(q.qual)
        for ci in self.classes.values():
            if ci.module is not mi:
                continue
            for m in ci.methods.values():
                for n in _walk_stmts(m.node):
                    if not isinstance(n, ast.Assign):
                        continue
                    t = self._ctor_class(n.value, mi)
                    if t is None:
                        continue
                    for tgt in n.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            ci.attr_types.setdefault(tgt.attr, t.qual)
        for stmt in mi.tree.body:
            if isinstance(stmt, ast.Assign):
                t = self._ctor_class(stmt.value, mi)
                if t is None:
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        mi.attr_types.setdefault(tgt.id, t.qual)

    def _ctor_class(self, value: ast.AST,
                    mi: ModuleInfo) -> Optional[ClassInfo]:
        if not isinstance(value, ast.Call):
            return None
        return self._resolve_class_expr(value.func, mi)

    def _resolve_class_expr(self, expr: ast.AST,
                            mi: ModuleInfo) -> Optional[ClassInfo]:
        if isinstance(expr, ast.Name):
            if expr.id in mi.classes:
                return mi.classes[expr.id]
            target = mi.imports.get(expr.id)
            if target is not None:
                return self.classes.get(target)
            return None
        if isinstance(expr, ast.Attribute):
            mod = self._module_of(expr.value, mi)
            if mod is not None:
                return mod.classes.get(expr.attr)
        return None

    def _module_of(self, expr: ast.AST,
                   mi: ModuleInfo) -> Optional[ModuleInfo]:
        """Resolve a Name/Attribute chain to an imported project
        module (`mod` or `pkg.sub`)."""
        dotted = _dotted(expr)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        target = mi.imports.get(head)
        if target is None:
            return None
        full = f"{target}.{rest}" if rest else target
        return self.modules.get(full)

    # -- lookup -------------------------------------------------------

    def find_method(self, cls_qual: str,
                    name: str) -> Optional[FuncInfo]:
        seen = set()
        queue = [cls_qual]
        while queue:
            q = queue.pop(0)
            if q in seen:
                continue
            seen.add(q)
            ci = self.classes.get(q)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            queue.extend(ci.base_quals)
        return None

    def attr_type(self, cls_qual: str, attr: str) -> Optional[str]:
        seen = set()
        queue = [cls_qual]
        while queue:
            q = queue.pop(0)
            if q in seen:
                continue
            seen.add(q)
            ci = self.classes.get(q)
            if ci is None:
                continue
            if attr in ci.attr_types:
                return ci.attr_types[attr]
            queue.extend(ci.base_quals)
        return None

    def resolve_call(self, func_expr: ast.AST,
                     scope: FuncInfo) -> Optional[FuncInfo]:
        """The FuncInfo a call expression lands in, or None."""
        mi = scope.module
        if isinstance(func_expr, ast.Name):
            r = self._resolve_name(func_expr.id, scope)
            return self._as_func(r)
        if not isinstance(func_expr, ast.Attribute):
            return None
        attr, value = func_expr.attr, func_expr.value
        if (isinstance(value, ast.Name) and value.id in ("self", "cls")
                and scope.cls is not None):
            return self.find_method(scope.cls.qual, attr)
        if isinstance(value, ast.Name):
            # imported module / imported-or-local class / instance
            mod = self.modules.get(mi.imports.get(value.id, ""))
            if mod is not None:
                return self._as_func(
                    mod.functions.get(attr) or mod.classes.get(attr))
            r = self._resolve_name(value.id, scope)
            if isinstance(r, ClassInfo):
                return self.find_method(r.qual, attr)
            inst = mi.attr_types.get(value.id)
            if inst is not None:
                return self.find_method(inst, attr)
            return None
        if isinstance(value, ast.Attribute):
            # self.X.meth() via inferred attr type
            if (isinstance(value.value, ast.Name)
                    and value.value.id == "self"
                    and scope.cls is not None):
                t = self.attr_type(scope.cls.qual, value.attr)
                if t is not None:
                    return self.find_method(t, attr)
                return None
            mod = self._module_of(value, mi)
            if mod is not None:
                return self._as_func(
                    mod.functions.get(attr) or mod.classes.get(attr))
        return None

    def _resolve_name(
            self, name: str, scope: FuncInfo,
    ) -> Optional[Union[FuncInfo, ClassInfo]]:
        fn: Optional[FuncInfo] = scope
        while fn is not None:
            if name in fn.nested:
                return fn.nested[name]
            fn = fn.parent
        mi = scope.module
        if name in mi.functions:
            return mi.functions[name]
        if name in mi.classes:
            return mi.classes[name]
        target = mi.imports.get(name)
        if target is not None:
            got = self.functions.get(target) or self.classes.get(target)
            if got is not None:
                return got
        return None

    def _as_func(self, r) -> Optional[FuncInfo]:
        if isinstance(r, FuncInfo):
            return r
        if isinstance(r, ClassInfo):
            return self.find_method(r.qual, "__init__")
        return None

    def all_functions(self) -> List[FuncInfo]:
        return list(self.functions.values())


def _dotted(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _dotted(expr.value)
        return None if base is None else f"{base}.{expr.attr}"
    return None
