"""raylint-xp — whole-program analysis on top of the per-file pass.

The per-file rules in ``raylint`` see one module at a time; this
package builds a project-wide index (module/symbol table, call graph,
per-function lock summaries) and runs the analyses that need it:

- ``xp-lock-order-inversion`` — two locks acquired in opposite orders
  along *call chains that cross functions and files*. The per-file
  rule only sees both orders when they are nested directly inside one
  module; here held-lock sets are propagated through the call graph,
  so ``A.flush()`` holding ``A_LOCK`` while calling into ``b.push()``
  (which takes ``B_LOCK``) conflicts with ``b.deliver()`` holding
  ``B_LOCK`` while calling back into ``a.apply_update()``.
- ``proto-orphan-sent`` — a ``{"type": X, ...}`` message flows into a
  send call site but no handler anywhere dispatches on ``X``.
- ``proto-orphan-handled`` — a handler dispatches on ``X`` but no
  send site in the tree ever produces it (dead protocol arms — or a
  sender that lives outside Python, which belongs in the baseline
  with a reason).
- ``proto-missing-field`` — the handler path for type ``X`` reads
  ``msg["k"]`` (a KeyError on absence) but no sender of ``X`` ever
  provides ``k``.

Whole-program findings cannot be suppressed with inline comments (no
single line owns them); the checked-in baseline
(``devtools/xp/baseline.json``) is their suppression mechanism, and
every entry must carry a reason. ``stale-baseline`` keeps the file
honest: an entry that matches nothing is itself a finding.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Tuple

from .index import ProjectIndex
from . import lockgraph, protocol, report
from .report import (apply_baseline, default_baseline_path, to_json,
                     to_sarif)

# name -> one-line doc, mirrored by `raylint --list-rules`
XP_RULES: Dict[str, str] = {
    "xp-lock-order-inversion":
        "two locks acquired in opposite orders along call chains "
        "crossing functions/files (held-set propagation)",
    "proto-orphan-sent":
        "a {\"type\": X} message is sent but no handler dispatches "
        "on X",
    "proto-orphan-handled":
        "a handler dispatches on X but no send site produces it",
    "proto-missing-field":
        "handler for X hard-reads msg[\"k\"] that no sender of X "
        "provides",
    "stale-baseline":
        "a baseline entry that no longer matches any finding",
    "xp-parse-error":
        "a file the whole-program index could not parse",
}

__all__ = [
    "XP_RULES", "ProjectIndex", "run_xp", "apply_baseline",
    "default_baseline_path", "to_json", "to_sarif",
]


def _roots(paths: Iterable[str]) -> List[str]:
    """Whole-program roots: directories as-is, files -> their dir."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        out.append(p if os.path.isdir(p) else os.path.dirname(p))
    # drop roots nested under another root (one index each is enough)
    out = sorted(set(out))
    kept: List[str] = []
    for p in out:
        if not any(p != q and p.startswith(q + os.sep) for q in out):
            kept.append(p)
    return kept


def run_xp(paths: Iterable[str], select: Optional[Iterable[str]] = None,
           ) -> Tuple[list, List[dict]]:
    """Run every whole-program pass over the package(s) rooted at
    `paths`. Returns (findings, wire-protocol inventory rows)."""
    from ..raylint import Finding  # late import; raylint imports us too

    wanted = set(select) if select else set(XP_RULES)
    findings: List[Finding] = []
    inventory: List[dict] = []
    for root in _roots(paths):
        idx = ProjectIndex.build(root)
        for path, line, msg in idx.errors:
            findings.append(Finding(path, line, "xp-parse-error", msg))
        if "xp-lock-order-inversion" in wanted:
            findings.extend(lockgraph.check(idx))
        proto_rules = {"proto-orphan-sent", "proto-orphan-handled",
                       "proto-missing-field"}
        if proto_rules & wanted:
            pfind, inv = protocol.check(idx)
            findings.extend(f for f in pfind if f.rule in wanted)
            inventory.extend(inv)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, inventory
