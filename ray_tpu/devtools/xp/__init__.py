"""raylint-xp — whole-program analysis on top of the per-file pass.

The per-file rules in ``raylint`` see one module at a time; this
package builds a project-wide index (module/symbol table, call graph,
per-function lock summaries) and runs the analyses that need it:

- ``xp-lock-order-inversion`` — two locks acquired in opposite orders
  along *call chains that cross functions and files*. The per-file
  rule only sees both orders when they are nested directly inside one
  module; here held-lock sets are propagated through the call graph,
  so ``A.flush()`` holding ``A_LOCK`` while calling into ``b.push()``
  (which takes ``B_LOCK``) conflicts with ``b.deliver()`` holding
  ``B_LOCK`` while calling back into ``a.apply_update()``.
- ``proto-orphan-sent`` — a ``{"type": X, ...}`` message flows into a
  send call site but no handler anywhere dispatches on ``X``.
- ``proto-orphan-handled`` — a handler dispatches on ``X`` but no
  send site in the tree ever produces it (dead protocol arms — or a
  sender that lives outside Python, which belongs in the baseline
  with a reason).
- ``proto-missing-field`` — the handler path for type ``X`` reads
  ``msg["k"]`` (a KeyError on absence) but no sender of ``X`` ever
  provides ``k``.
- the **remote-call contract checker** (``contracts``): every
  ``fn.remote(...)`` / ``Cls.remote(...)`` /
  ``handle.method.remote(...)`` site resolved against the decorated
  def — arity/kwargs/missing-args, ``.options(...)`` keys against the
  runtime's real option tables, and ``num_returns`` against the
  tuple-unpack arity at the site.
- the **ObjectRef lifetime analysis** (``reflife``): refs born from
  ``put()``/``.remote()`` and never consumed (fire-and-forget leaks),
  and the ``get()``-per-ref-inside-a-loop serialization anti-pattern.
- the **jit-purity / host-sync detector** (``jitlint``): the call
  graph walked from every ``jax.jit``/``pjit`` entry point; device->
  host syncs, Python-side mutation under trace, and broken
  ``static_argnums`` pins flag with the traced call chain attached.
- the **native-boundary analyses** (``ffi_sig``/``ffi_layout``/
  ``xlang``): a clang-free C++ extractor (:mod:`.cxx`) parses the
  ``extern "C"`` surface, struct layouts, lock/blocking behavior and
  message dispatch of ``src/``+``cpp/``; :mod:`.ffi` checks every
  ctypes ``argtypes``/``restype`` declaration, ``Structure`` mirror,
  pinned constant and wire-frame format against it, and derives the
  ``NATIVE_PLANE`` protocol annotation instead of trusting it;
  :func:`.lockgraph.check_xlang` propagates held-lock sets across the
  boundary in both directions.

Whole-program findings cannot be suppressed with inline comments (no
single line owns them); the checked-in baseline
(``devtools/xp/baseline.json``) is their suppression mechanism, and
every entry must carry a reason. ``stale-baseline`` keeps the file
honest: an entry that matches nothing is itself a finding.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Tuple

from .index import ProjectIndex
from . import lockgraph, protocol, report
from .report import (apply_baseline, default_baseline_path, to_json,
                     to_sarif)

# name -> one-line doc, mirrored by `raylint --list-rules`
XP_RULES: Dict[str, str] = {
    "xp-lock-order-inversion":
        "two locks acquired in opposite orders along call chains "
        "crossing functions/files (held-set propagation)",
    "proto-orphan-sent":
        "a {\"type\": X} message is sent but no handler dispatches "
        "on X",
    "proto-orphan-handled":
        "a handler dispatches on X but no send site produces it",
    "proto-missing-field":
        "handler for X hard-reads msg[\"k\"] that no sender of X "
        "provides",
    "xp-remote-signature":
        "a .remote(...) call that does not fit the decorated "
        "signature (arity, unknown kwarg, missing required arg, or "
        "a method the actor class never defines)",
    "xp-remote-options":
        ".options(...)/@remote(...) keys outside the runtime's real "
        "option tables, or task-only options on actors (and vice "
        "versa)",
    "xp-remote-num-returns":
        "tuple-unpack arity at a .remote() call site disagrees with "
        "the declared num_returns",
    "xp-ref-leak":
        "an ObjectRef from put()/.remote() that is never consumed "
        "(discarded expression or a binding with no later use)",
    "xp-ref-get-in-loop":
        "get(one_ref) inside a loop over a list of refs — serializes "
        "the fan-out behind one round-trip per element",
    "xp-jit-host-sync":
        "a device->host sync (.item()/np.asarray/print/float-cast) "
        "reachable from a jax.jit entry point",
    "xp-jit-impure-mutation":
        "self.<attr> or global/nonlocal mutation inside jit-traced "
        "code (runs at trace time only)",
    "xp-jit-static-args":
        "static_argnums/static_argnames out of range, naming a "
        "missing parameter, or receiving an unhashable literal",
    "xp-ffi-signature":
        "a ctypes argtypes/restype declaration that disagrees with "
        "the parsed extern \"C\" signature (arity, width/signedness, "
        "pointer-vs-value, undeclared export, or a call with no "
        "declaration at all)",
    "xp-ffi-layout":
        "a ctypes.Structure mirror, # cxx-const pin, or # cxx-wire "
        "frame format that drifted from the C++ struct layout, "
        "constant value, or wire annotation",
    "xp-xlang-protocol":
        "a NATIVE_PLANE annotation key no C++ dispatch arm mentions "
        "(stale), or a natively-dispatched type the annotation "
        "misses",
    "xp-xlang-lock":
        "a lock held across the FFI boundary into unbounded blocking "
        "(Python lock -> joining native export; C++ mutex -> "
        "PyGILState_Ensure)",
    "xp-graph-unsafe-capture":
        "a side effect (self/global mutation, wall-clock or "
        "randomness read, I/O) reachable from a graph-capture entry "
        "point — replayed frames skip the Python between "
        "submissions, so the effect runs at capture time only",
    "xp-graph-shape-drift":
        "the captured graph's shape depends on runtime values: a "
        "branch/loop bound on a get()-derived value guarding "
        "submissions, a feedback edge, an edge out of a "
        "num_returns=0 producer, or a resource annotation that can "
        "never be scheduled as captured",
    "xp-graph-ref-escape":
        "a captured ref stashed into global/self — on replay the "
        "stash aliases the capture iteration's (stale) channel",
    "xp-graph-actor-order":
        "two branches submit to the same actors in opposite orders "
        "— capture fixes one submission order per actor, so "
        "replaying the other branch reorders cross-actor effects",
    "stale-baseline":
        "a baseline entry that no longer matches any finding",
    "xp-parse-error":
        "a file the whole-program index could not parse",
    "cxx-parse-error":
        "an extern \"C\" declaration the C++ extractor could not "
        "parse (the boundary checks are blind to it)",
}

# analysis name -> the rule ids it owns (drives --select routing and
# the --stats per-analysis summary)
ANALYSIS_RULES: Dict[str, frozenset] = {
    "lockgraph": frozenset({"xp-lock-order-inversion"}),
    "protocol": frozenset({"proto-orphan-sent", "proto-orphan-handled",
                           "proto-missing-field"}),
    "contracts": frozenset({"xp-remote-signature", "xp-remote-options",
                            "xp-remote-num-returns"}),
    "reflife": frozenset({"xp-ref-leak", "xp-ref-get-in-loop"}),
    "jitlint": frozenset({"xp-jit-host-sync", "xp-jit-impure-mutation",
                          "xp-jit-static-args"}),
    "effects": frozenset({"xp-graph-unsafe-capture"}),
    "graphcap": frozenset({"xp-graph-shape-drift",
                           "xp-graph-ref-escape",
                           "xp-graph-actor-order"}),
    "ffi_sig": frozenset({"xp-ffi-signature"}),
    "ffi_layout": frozenset({"xp-ffi-layout"}),
    "xlang": frozenset({"xp-xlang-protocol", "xp-xlang-lock"}),
}

# rules that need the C++ side parsed at all
_CXX_RULES = (ANALYSIS_RULES["ffi_sig"] | ANALYSIS_RULES["ffi_layout"]
              | ANALYSIS_RULES["xlang"] | {"cxx-parse-error"})

__all__ = [
    "XP_RULES", "ANALYSIS_RULES", "ProjectIndex", "run_xp",
    "apply_baseline", "default_baseline_path", "to_json", "to_sarif",
]


def _roots(paths: Iterable[str]) -> List[str]:
    """Whole-program roots: directories as-is, files -> their dir."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        out.append(p if os.path.isdir(p) else os.path.dirname(p))
    # drop roots nested under another root (one index each is enough)
    out = sorted(set(out))
    kept: List[str] = []
    for p in out:
        if not any(p != q and p.startswith(q + os.sep) for q in out):
            kept.append(p)
    return kept


def run_xp(paths: Iterable[str], select: Optional[Iterable[str]] = None,
           stats: Optional[dict] = None,
           only: Optional[set] = None,
           graphs: Optional[list] = None) -> Tuple[list, List[dict]]:
    """Run every whole-program pass over the package(s) rooted at
    `paths`. Returns (findings, wire-protocol inventory rows). When
    `stats` is a dict it is filled in place with index size, call-graph
    edge count, and per-analysis finding counts. `only` (a set of
    absolute file paths — the --changed-only diff) keeps indexing and
    provenance whole-program but restricts the per-site scans of the
    site-anchored analyses (contracts/reflife/jitlint/effects/graphcap)
    to functions in those files; the graph analyses
    (lockgraph/protocol) still run in full, since their table builds
    are their scans. When `graphs` is a list it is filled in place
    with the per-entry task-graph artifacts from graph capture
    (``raylint --graph-out``)."""
    from ..raylint import Finding  # late import; raylint imports us too
    from . import contracts, cxx, effects, ffi, graphcap, jitlint, \
        reflife
    from .dataflow import CallGraph, RemoteResolver

    wanted = set(select) if select else set(XP_RULES)
    findings: List[Finding] = []
    inventory: List[dict] = []

    def record(analysis: str, got: List[Finding]) -> None:
        kept = [f for f in got if f.rule in wanted]
        findings.extend(kept)
        if stats is not None:
            per = stats.setdefault("analyses", {})
            per[analysis] = per.get(analysis, 0) + len(kept)

    for root in _roots(paths):
        idx = ProjectIndex.build(root)
        graph = CallGraph(idx)
        if stats is not None:
            stats["files"] = stats.get("files", 0) + len(idx.modules)
            stats["call_edges"] = (stats.get("call_edges", 0)
                                   + graph.edge_count)
        for path, line, msg in idx.errors:
            findings.append(Finding(path, line, "xp-parse-error", msg))
        # The graph analyses' whole-tree scans ARE their table builds,
        # so scoping buys them nothing — in the incremental pre-commit
        # path they are skipped (the tier-1 gate runs them in full).
        # An explicit --select overrides the skip.
        run_graph = only is None or select is not None
        cxx_idx = None
        if (_CXX_RULES | ANALYSIS_RULES["protocol"]) & wanted \
                and run_graph:
            cxx_idx = cxx.build(root)
            if stats is not None:
                stats["cxx_files"] = (stats.get("cxx_files", 0)
                                      + len(cxx_idx.files))
                stats["cxx_exports"] = (
                    stats.get("cxx_exports", 0)
                    + sum(1 for n in cxx_idx.functions
                          if (f := cxx_idx.lookup(n)) is not None
                          and f.exported))
            if "cxx-parse-error" in wanted:
                for path, line, msg in cxx_idx.errors:
                    findings.append(
                        Finding(path, line, "cxx-parse-error", msg))
        lock_scans = None
        if (ANALYSIS_RULES["lockgraph"]
                | ANALYSIS_RULES["xlang"]) & wanted and run_graph:
            lock_scans = lockgraph.scan_all(idx)
        if ANALYSIS_RULES["lockgraph"] & wanted and run_graph:
            record("lockgraph", lockgraph.check(idx, scans=lock_scans))
        if ANALYSIS_RULES["protocol"] & wanted and run_graph:
            pfind, inv = protocol.check(idx, cxx_idx=cxx_idx)
            record("protocol", pfind)
            inventory.extend(inv)
        if _CXX_RULES & wanted and run_graph and cxx_idx is not None:
            pyscan = ffi.scan_python(idx)
            if ANALYSIS_RULES["ffi_sig"] & wanted:
                record("ffi_sig",
                       ffi.check_signatures(idx, cxx_idx, pyscan))
            if ANALYSIS_RULES["ffi_layout"] & wanted:
                record("ffi_layout",
                       ffi.check_layouts(idx, cxx_idx, pyscan))
            if ANALYSIS_RULES["xlang"] & wanted:
                xl = ffi.check_protocol(idx, cxx_idx, pyscan)
                xl += lockgraph.check_xlang(idx, cxx_idx,
                                            scans=lock_scans)
                record("xlang", xl)
        resolver = None
        if (ANALYSIS_RULES["contracts"] | ANALYSIS_RULES["reflife"]
                | ANALYSIS_RULES["effects"]
                | ANALYSIS_RULES["graphcap"]) & wanted:
            # one resolver (and one provenance fixed point) shared by
            # all handle-flow analyses — building it dominates their
            # cost
            resolver = RemoteResolver(idx)
        if ANALYSIS_RULES["contracts"] & wanted:
            record("contracts",
                   contracts.check(idx, resolver=resolver, only=only))
        if ANALYSIS_RULES["reflife"] & wanted:
            record("reflife",
                   reflife.check(idx, resolver=resolver, only=only))
        if ANALYSIS_RULES["jitlint"] & wanted:
            record("jitlint", jitlint.check(idx, graph=graph, only=only))
        if ANALYSIS_RULES["graphcap"] & wanted or graphs is not None:
            # capture runs whenever its rules are wanted OR an
            # artifact was requested; stats always get the graph
            # counts so --stats parity holds without --graph-out
            glist = graphs if graphs is not None else []
            n0 = len(glist)
            got = graphcap.check(idx, graph=graph, resolver=resolver,
                                 only=only, graphs=glist)
            if ANALYSIS_RULES["graphcap"] & wanted:
                record("graphcap", got)
            if stats is not None:
                new = glist[n0:]
                stats["graph_entries"] = (stats.get("graph_entries", 0)
                                          + len(new))
                stats["graph_nodes"] = (
                    stats.get("graph_nodes", 0)
                    + sum(len(g["nodes"]) for g in new))
                stats["graph_edges"] = (
                    stats.get("graph_edges", 0)
                    + sum(len(g["edges"]) for g in new))
        if ANALYSIS_RULES["effects"] & wanted:
            record("effects",
                   effects.check(idx, graph=graph, resolver=resolver,
                                 only=only))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, inventory
