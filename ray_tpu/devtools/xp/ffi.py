"""Cross-language FFI analyses: the ctypes side of the native boundary.

:mod:`.cxx` parses the C++ half (``extern "C"`` signatures, struct
layouts, constants, wire-frame annotations, message dispatch); this
module parses the Python half — every ``lib.foo.argtypes``/``restype``
declaration, every call through a ctypes handle, ``ctypes.Structure``
mirrors, ``# cxx-const:`` / ``# cxx-wire:`` pins — and checks the two
against each other:

- ``xp-ffi-signature`` — arity, width/signedness class and
  pointer-vs-value of every declaration vs the parsed ``extern "C"``
  signature; declarations for exports no C++ file defines; Python
  calls to exports that declare neither ``argtypes`` nor ``restype``
  (ctypes' int defaults truncate 64-bit handles); and two ``extern
  "C"`` declarations of one symbol that disagree (hand-copied blocks
  in harnesses/clients drifting from the definition).
- ``xp-ffi-layout`` — ``ctypes.Structure`` mirrors vs the C struct
  layout (field count, byte width, array length); ``# cxx-const:``
  pins vs the C++ constant value; ``# cxx-wire:`` struct format
  strings vs the ``// cxx-wire:`` frame annotation next to the C++
  read/write code (endianness prefix included).
- ``xp-xlang-protocol`` — every ``NATIVE_PLANE``-style annotation
  dict checked against the *derived* C++ dispatch inventory: a key no
  handler loop mentions is stale; a message type the native plane
  dispatches or constructs without an annotation is missing.

Cross-boundary lock propagation (the third leg of the cxx tentpole)
lives in :func:`.lockgraph.check_xlang`, which consumes the same C++
index.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .cxx import CType, CxxIndex
from .index import ProjectIndex

# ---------------------------------------------------------------------------
# ctypes type model (mapped onto cxx.CType)
# ---------------------------------------------------------------------------

_CT_SCALARS: Dict[str, Tuple[str, int]] = {
    "c_bool": ("uint", 8),
    "c_char": ("int", 8),
    "c_byte": ("int", 8),
    "c_ubyte": ("uint", 8),
    "c_int8": ("int", 8),
    "c_uint8": ("uint", 8),
    "c_short": ("int", 16),
    "c_ushort": ("uint", 16),
    "c_int16": ("int", 16),
    "c_uint16": ("uint", 16),
    "c_int": ("int", 32),
    "c_uint": ("uint", 32),
    "c_int32": ("int", 32),
    "c_uint32": ("uint", 32),
    "c_long": ("int", 64),          # LP64, like the C side
    "c_ulong": ("uint", 64),
    "c_longlong": ("int", 64),
    "c_ulonglong": ("uint", 64),
    "c_int64": ("int", 64),
    "c_uint64": ("uint", 64),
    "c_size_t": ("uint", 64),
    "c_ssize_t": ("int", 64),
    "c_float": ("float", 32),
    "c_double": ("float", 64),
}

_LIBISH = re.compile(r"(^|_)(lib|dll|cdll|so)$", re.IGNORECASE)


def _terminal(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Call):
        return _terminal(expr.func)
    return None


def _libish(expr: ast.AST) -> bool:
    name = _terminal(expr)
    return bool(name and _LIBISH.search(name) or name == "_load")


@dataclass
class PyType:
    ctype: Optional[CType]       # None -> unparseable expression
    count: int = 1               # >1 for `c_uint8 * 28` array mirrors
    spelled: str = ""


def _py_ctype(node: ast.AST) -> PyType:
    """A ctypes type expression -> PyType."""
    if isinstance(node, ast.Constant) and node.value is None:
        return PyType(CType("void"), spelled="None")
    name = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    if name is not None:
        if name in _CT_SCALARS:
            kind, width = _CT_SCALARS[name]
            return PyType(CType(kind, width, spelled=name), spelled=name)
        if name == "c_char_p":
            return PyType(CType("ptr", 64, CType("int", 8),
                                spelled=name), spelled=name)
        if name == "c_void_p":
            return PyType(CType("ptr", 64, CType("void"),
                                spelled=name), spelled=name)
        if name == "c_wchar_p":
            return PyType(CType("ptr", 64, CType("opaque"),
                                spelled=name), spelled=name)
        # a Structure subclass (or anything else) by name
        return PyType(CType("opaque", spelled=name), spelled=name)
    if isinstance(node, ast.Call) and _terminal(node.func) == "POINTER" \
            and len(node.args) == 1:
        inner = _py_ctype(node.args[0])
        sp = f"POINTER({inner.spelled})"
        if inner.ctype is None:
            return PyType(None, spelled=sp)
        return PyType(CType("ptr", 64, inner.ctype, spelled=sp),
                      spelled=sp)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        elem = _py_ctype(node.left)
        if isinstance(node.right, ast.Constant) \
                and isinstance(node.right.value, int) \
                and elem.ctype is not None:
            return PyType(elem.ctype, count=node.right.value,
                          spelled=f"{elem.spelled} * {node.right.value}")
    try:
        sp = ast.unparse(node)
    except Exception:
        sp = "<?>"
    return PyType(None, spelled=sp)


# ---------------------------------------------------------------------------
# Python-side scan
# ---------------------------------------------------------------------------


@dataclass
class PyDecl:
    sym: str
    path: str
    argtypes: Optional[List[PyType]] = None
    restype: Optional[PyType] = None      # None -> never assigned
    arg_line: int = 0
    res_line: int = 0

    @property
    def line(self) -> int:
        return self.arg_line or self.res_line


@dataclass
class PyScan:
    decls: Dict[str, List[PyDecl]] = field(default_factory=dict)
    calls: Dict[str, List[Tuple[str, int]]] = field(default_factory=dict)
    # NATIVE_PLANE-style dicts: (path, line, {key: key_line})
    native_planes: List[Tuple[str, int, Dict[str, int]]] = \
        field(default_factory=list)
    # (path, line, python name, value, C++ constant name)
    const_pins: List[Tuple[str, int, str, object, str]] = \
        field(default_factory=list)
    # (path, line, frame name, fmt or None)
    wire_pins: List[Tuple[str, int, str, Optional[str]]] = \
        field(default_factory=list)
    # (path, line, class name, [(field name, PyType)] or None)
    mirrors: List[Tuple[str, int, str,
                        Optional[List[Tuple[str, PyType]]]]] = \
        field(default_factory=list)


def _decl_for(scan: PyScan, sym: str, path: str) -> PyDecl:
    for d in scan.decls.setdefault(sym, []):
        if d.path == path:
            return d
    d = PyDecl(sym, path)
    scan.decls[sym].append(d)
    return d


_CONST_PIN_RE = re.compile(r"#\s*cxx-const:\s*(\w+)")
_WIRE_PIN_RE = re.compile(r"#\s*cxx-wire:\s*([\w-]+)")
_FMT_RE = re.compile(r"""["']([<>=!@]?[0-9xcbBhHiIlLqQnNefdspP]+)["']""")


def _scan_module_source(path: str, tree: ast.Module,
                        scan: PyScan) -> None:
    """Comment-pin extraction (# cxx-const / # cxx-wire) — needs the
    raw source, the AST does not carry comments."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
    except OSError:
        return
    if "cxx-const:" not in src and "cxx-wire:" not in src:
        return
    # line -> (name, int value) for simple constant assignments
    consts: Dict[int, Tuple[str, object]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, (int, bytes, str)):
            consts[node.lineno] = (node.targets[0].id, node.value.value)
    for i, text in enumerate(src.splitlines(), start=1):
        m = _CONST_PIN_RE.search(text)
        if m is not None:
            if i in consts:
                pyname, val = consts[i]
                scan.const_pins.append((path, i, pyname, val, m.group(1)))
            else:
                scan.const_pins.append((path, i, "", None, m.group(1)))
        m = _WIRE_PIN_RE.search(text)
        if m is not None:
            fm = _FMT_RE.search(text.split("#")[0])
            scan.wire_pins.append(
                (path, i, m.group(1), fm.group(1) if fm else None))


def scan_python(idx: ProjectIndex) -> PyScan:
    scan = PyScan()
    for mod in idx.modules.values():
        tree = mod.tree
        path = mod.path
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                # lib.foo.argtypes = [...] / lib.foo.restype = ...
                if isinstance(tgt, ast.Attribute) \
                        and tgt.attr in ("argtypes", "restype") \
                        and isinstance(tgt.value, ast.Attribute) \
                        and _libish(tgt.value.value):
                    d = _decl_for(scan, tgt.value.attr, path)
                    if tgt.attr == "argtypes":
                        if isinstance(node.value, (ast.List, ast.Tuple)):
                            d.argtypes = [_py_ctype(e)
                                          for e in node.value.elts]
                        else:
                            d.argtypes = []
                        d.arg_line = node.lineno
                    else:
                        d.restype = _py_ctype(node.value)
                        d.res_line = node.lineno
                # NATIVE_PLANE = {...}
                elif isinstance(tgt, ast.Name) \
                        and tgt.id == "NATIVE_PLANE" \
                        and isinstance(node.value, ast.Dict):
                    keys: Dict[str, int] = {}
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys[k.value] = k.lineno
                    scan.native_planes.append(
                        (path, node.lineno, keys))
            # for name in ("a", "b"): fn = getattr(lib, name); fn...
            elif isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name) \
                    and isinstance(node.iter, (ast.Tuple, ast.List)):
                syms = [e.value for e in node.iter.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                if not syms:
                    continue
                alias = None
                for stmt in node.body:
                    if not isinstance(stmt, ast.Assign) \
                            or len(stmt.targets) != 1:
                        continue
                    tgt = stmt.targets[0]
                    if isinstance(tgt, ast.Name) \
                            and isinstance(stmt.value, ast.Call) \
                            and _terminal(stmt.value.func) == "getattr" \
                            and len(stmt.value.args) == 2 \
                            and _libish(stmt.value.args[0]):
                        alias = tgt.id
                    elif alias is not None \
                            and isinstance(tgt, ast.Attribute) \
                            and tgt.attr in ("argtypes", "restype") \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == alias:
                        for sym in syms:
                            d = _decl_for(scan, sym, path)
                            if tgt.attr == "argtypes":
                                if isinstance(stmt.value,
                                              (ast.List, ast.Tuple)):
                                    d.argtypes = [
                                        _py_ctype(e)
                                        for e in stmt.value.elts]
                                else:
                                    d.argtypes = []
                                d.arg_line = stmt.lineno
                            else:
                                d.restype = _py_ctype(stmt.value)
                                d.res_line = stmt.lineno
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr not in ("argtypes", "restype") \
                    and _libish(node.func.value):
                scan.calls.setdefault(node.func.attr, []).append(
                    (path, node.lineno))
        for cls in mod.classes.values():
            bases = [_terminal(b) for b in cls.base_exprs]
            if "Structure" not in bases:
                continue
            fields: Optional[List[Tuple[str, PyType]]] = None
            for stmt in cls.node.body:
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and stmt.targets[0].id == "_fields_" \
                        and isinstance(stmt.value, (ast.List, ast.Tuple)):
                    fields = []
                    for e in stmt.value.elts:
                        if isinstance(e, (ast.Tuple, ast.List)) \
                                and len(e.elts) >= 2 \
                                and isinstance(e.elts[0], ast.Constant):
                            fields.append((e.elts[0].value,
                                           _py_ctype(e.elts[1])))
            scan.mirrors.append(
                (path, cls.node.lineno, cls.name, fields))
        _scan_module_source(path, tree, scan)
    return scan


# ---------------------------------------------------------------------------
# Type compatibility
# ---------------------------------------------------------------------------


def _pointee_compat(py: CType, c: CType) -> Optional[str]:
    if py.kind in ("void", "opaque") or c.kind in ("void", "opaque"):
        return None
    if py.kind == "ptr" and c.kind == "ptr":
        return None
    if py.kind == "ptr" or c.kind == "ptr":
        return (f"points at {py.pretty()} but C expects a pointer to "
                f"{c.pretty()}")
    if py.width == 8 and c.width == 8:
        return None                      # char* ~ uint8_t*: byte class
    if py.width != c.width:
        return (f"points at a {py.width}-bit value but C expects a "
                f"pointer to a {c.width}-bit {c.pretty()}")
    if py.kind != c.kind and py.kind in ("int", "uint") \
            and c.kind in ("int", "uint"):
        return (f"pointee signedness differs ({py.pretty()} vs "
                f"{c.pretty()})")
    return None


def _compat(py: CType, c: CType) -> Optional[str]:
    """None when compatible, else a short mismatch description."""
    if c.kind == "opaque" or py.kind == "opaque":
        return None
    if c.kind == "void":
        return None if py.kind == "void" else \
            f"declares {py.pretty()} but C returns void"
    if py.kind == "void":
        return f"declares None but C has {c.pretty()}"
    if c.kind == "ptr" and py.kind != "ptr":
        return (f"passes {py.pretty()} by value but C expects "
                f"{c.pretty()} (pointer-vs-value)")
    if py.kind == "ptr" and c.kind != "ptr":
        return (f"passes a pointer ({py.pretty()}) but C expects "
                f"{c.pretty()} by value (pointer-vs-value)")
    if c.kind == "ptr":
        sub = _pointee_compat(py.pointee, c.pointee)
        return None if sub is None else f"{py.pretty()} {sub}"
    if c.kind == "float" or py.kind == "float":
        if c.kind != py.kind:
            return f"{py.pretty()} vs {c.pretty()} (class mismatch)"
        return None if c.width == py.width else \
            f"{py.pretty()} is {py.width}-bit, C {c.pretty()} is " \
            f"{c.width}-bit"
    # both integer classes
    if py.width != c.width:
        return (f"{py.pretty()} is {py.width}-bit but C {c.pretty()} "
                f"is {c.width}-bit (width mismatch)")
    if c.width > 8 and py.kind != c.kind:
        return (f"{py.pretty()} vs {c.pretty()}: signedness differs")
    return None


def _site(path: str, line: int) -> str:
    return f"{os.path.relpath(path)}:{line}" if os.path.isabs(path) \
        else f"{path}:{line}"


# ---------------------------------------------------------------------------
# Analyses
# ---------------------------------------------------------------------------


def check_signatures(idx: ProjectIndex, cxx_idx: CxxIndex,
                     scan: Optional[PyScan] = None) -> List:
    from ..raylint import Finding

    scan = scan if scan is not None else scan_python(idx)
    findings: List[Finding] = []

    # drift between extern "C" occurrences of one symbol (definition
    # vs the hand-copied declaration blocks in harnesses/clients)
    for sym in sorted(cxx_idx.functions):
        occ = [f for f in cxx_idx.functions[sym] if f.exported]
        base = cxx_idx.lookup(sym)
        if base is None:
            continue
        for other in occ:
            if other is base:
                continue
            why = None
            if len(other.params) != len(base.params):
                why = (f"{len(other.params)} parameter(s) vs "
                       f"{len(base.params)}")
            else:
                for i, (a, b) in enumerate(
                        zip(other.params, base.params)):
                    sub = _compat(a, b)
                    if sub:
                        why = f"parameter {i + 1}: {sub}"
                        break
                if why is None:
                    sub = _compat(other.ret, base.ret)
                    if sub:
                        why = f"return type: {sub}"
            if why:
                findings.append(Finding(
                    other.path, other.line, "xp-ffi-signature",
                    f'conflicting extern "C" declarations of '
                    f"`{sym}`: this one says `{other.sig()}` but the "
                    f"definition at {_site(base.path, base.line)} is "
                    f"`{base.sig()}` — {why}"))

    for sym in sorted(scan.decls):
        cf = cxx_idx.lookup(sym)
        for d in scan.decls[sym]:
            if cf is None or not cf.exported:
                findings.append(Finding(
                    d.path, d.line, "xp-ffi-signature",
                    f"ctypes declaration for `{sym}` but no extern "
                    f'"C" symbol with that name exists in '
                    f"{_cc_summary(cxx_idx)} (undeclared export — "
                    f"typo, or the C side was removed)"))
                continue
            if d.argtypes is not None \
                    and len(d.argtypes) != len(cf.params):
                findings.append(Finding(
                    d.path, d.arg_line, "xp-ffi-signature",
                    f"`{sym}` declares {len(d.argtypes)} argtypes but "
                    f"the C signature at {_site(cf.path, cf.line)} is "
                    f"`{cf.sig()}` ({len(cf.params)} parameter(s)) — "
                    f"arity mismatch"))
            elif d.argtypes is not None:
                for i, (py, c) in enumerate(zip(d.argtypes, cf.params)):
                    if py.ctype is None:
                        continue
                    sub = _compat(py.ctype, c)
                    if sub:
                        pname = (cf.param_names[i]
                                 if i < len(cf.param_names) else "")
                        pdesc = f"`{pname}` " if pname else ""
                        findings.append(Finding(
                            d.path, d.arg_line, "xp-ffi-signature",
                            f"`{sym}` argtypes[{i}]: {sub} — C "
                            f"parameter {i + 1} {pdesc}at "
                            f"{_site(cf.path, cf.line)} is "
                            f"`{cf.params[i].pretty()}`"))
            if d.restype is not None and d.restype.ctype is not None:
                sub = _compat(d.restype.ctype, cf.ret)
                if sub:
                    findings.append(Finding(
                        d.path, d.res_line, "xp-ffi-signature",
                        f"`{sym}` restype: {sub} — C returns "
                        f"`{cf.ret.pretty()}` at "
                        f"{_site(cf.path, cf.line)}"))
            elif d.restype is None \
                    and (cf.ret.kind == "ptr"
                         or (cf.ret.kind in ("int", "uint")
                             and cf.ret.width > 32)):
                findings.append(Finding(
                    d.path, d.line, "xp-ffi-signature",
                    f"`{sym}` declares argtypes but no restype — C "
                    f"returns `{cf.ret.pretty()}` at "
                    f"{_site(cf.path, cf.line)} and ctypes' default "
                    f"c_int restype truncates it to 32 bits"))

    for sym in sorted(scan.calls):
        if sym in scan.decls:
            continue
        cf = cxx_idx.lookup(sym)
        path, line = scan.calls[sym][0]
        if cf is not None and cf.exported:
            findings.append(Finding(
                path, line, "xp-ffi-signature",
                f"call to `{sym}` but no argtypes/restype are ever "
                f"declared for it — ctypes applies int defaults "
                f"(64-bit values truncate) against `{cf.sig()}` at "
                f"{_site(cf.path, cf.line)}"))
        elif cxx_idx.files:
            findings.append(Finding(
                path, line, "xp-ffi-signature",
                f"call to `{sym}` through a ctypes handle but no "
                f'extern "C" symbol with that name exists in '
                f"{_cc_summary(cxx_idx)} (undeclared export)"))
    return findings


def _cc_summary(cxx_idx: CxxIndex) -> str:
    names = sorted({os.path.basename(p) for p in cxx_idx.files
                    if not p.endswith(".h")})
    if len(names) > 4:
        names = names[:4] + ["…"]
    return "/".join(names) if names else "the C++ sources"


def _struct_flat(cxx_idx: CxxIndex, name: str):
    st = cxx_idx.structs.get(name)
    return st


def check_layouts(idx: ProjectIndex, cxx_idx: CxxIndex,
                  scan: Optional[PyScan] = None) -> List:
    from ..raylint import Finding

    scan = scan if scan is not None else scan_python(idx)
    findings: List[Finding] = []

    for path, line, pyname, val, cname in scan.const_pins:
        if cname not in cxx_idx.constants:
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"`# cxx-const: {cname}` pins a constant no C++ "
                f"source defines (checked {_cc_summary(cxx_idx)})"))
            continue
        cval, cpath, cline = cxx_idx.constants[cname]
        if val is None:
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"`# cxx-const: {cname}` must annotate a simple "
                f"`NAME = <literal>` assignment on the same line"))
        elif val != cval:
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"`{pyname}` = {val!r} but C++ `{cname}` = {cval} at "
                f"{_site(cpath, cline)} — layout/protocol drift"))

    for path, line, frame, fmt in scan.wire_pins:
        if frame not in cxx_idx.wire:
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"`# cxx-wire: {frame}` references a frame no "
                f"`// cxx-wire:` annotation in the C++ sources "
                f"declares"))
            continue
        cfmt, cpath, cline = cxx_idx.wire[frame]
        # "!" (network order) and ">" are the same layout
        norm = (lambda s: s.replace("!", ">") if s else s)
        if fmt is None:
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"`# cxx-wire: {frame}` must sit on the line with "
                f"the struct format string"))
        elif norm(fmt) != norm(cfmt):
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f'wire frame "{frame}" uses format "{fmt}" but the '
                f'C++ side at {_site(cpath, cline)} declares "{cfmt}"'
                f" — byte order/width drift on the wire"))

    for path, line, clsname, fields in scan.mirrors:
        st = cxx_idx.structs.get(clsname)
        if st is None:
            if cxx_idx.files:
                findings.append(Finding(
                    path, line, "xp-ffi-layout",
                    f"ctypes.Structure `{clsname}` mirrors no C++ "
                    f"struct of that name (checked "
                    f"{_cc_summary(cxx_idx)})"))
            continue
        if fields is None:
            continue
        if not st.mirrorable:
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"ctypes.Structure `{clsname}` mirrors C++ struct "
                f"`{st.name}` at {_site(st.path, st.line)} whose "
                f"layout is not fixed-width (pointers/opaque "
                f"members) — it cannot be mirrored safely"))
            continue
        if len(fields) != len(st.fields):
            findings.append(Finding(
                path, line, "xp-ffi-layout",
                f"`{clsname}` declares {len(fields)} field(s) but "
                f"C++ `{st.name}` at {_site(st.path, st.line)} has "
                f"{len(st.fields)} — struct layout drift"))
            continue
        for (pname, pt), cf in zip(fields, st.fields):
            where = (f"field `{cf.name}` of `{st.name}` "
                     f"({_site(st.path, cf.line)})")
            if pt.ctype is None:
                continue
            if pt.count != cf.count:
                findings.append(Finding(
                    path, line, "xp-ffi-layout",
                    f"`{clsname}.{pname}` is an array of {pt.count} "
                    f"but {where} has {cf.count} element(s)"))
                continue
            if cf.ctype.kind == "opaque":
                if pt.ctype.kind == "opaque" \
                        and pt.ctype.spelled == cf.ctype.spelled:
                    continue
                findings.append(Finding(
                    path, line, "xp-ffi-layout",
                    f"`{clsname}.{pname}` ({pt.spelled}) does not "
                    f"match the nested struct type "
                    f"`{cf.ctype.pretty()}` of {where}"))
                continue
            sub = _compat(pt.ctype, cf.ctype)
            if sub:
                findings.append(Finding(
                    path, line, "xp-ffi-layout",
                    f"`{clsname}.{pname}`: {sub} — {where}"))
    return findings


def check_protocol(idx: ProjectIndex, cxx_idx: CxxIndex,
                   scan: Optional[PyScan] = None) -> List:
    from ..raylint import Finding

    scan = scan if scan is not None else scan_python(idx)
    findings: List[Finding] = []
    native_types = set(cxx_idx.dispatch) | set(cxx_idx.surface_sent)

    annotated = set()
    for path, line, keys in scan.native_planes:
        annotated |= set(keys)
        for key, kline in sorted(keys.items()):
            if key not in native_types and key not in cxx_idx.sent:
                findings.append(Finding(
                    path, kline, "xp-xlang-protocol",
                    f'NATIVE_PLANE annotates "{key}" but no C++ '
                    f"dispatch arm or native send in "
                    f"{_cc_summary(cxx_idx)} mentions it — stale "
                    f"annotation (the native plane no longer "
                    f"implements this type)"))
    if scan.native_planes:
        np_path, np_line, _ = scan.native_planes[0]
        for t in sorted(native_types - annotated):
            cpath, cline = cxx_idx.dispatch.get(
                t, cxx_idx.surface_sent.get(t, ("", 0)))
            findings.append(Finding(
                cpath, cline, "xp-xlang-protocol",
                f'the native plane dispatches/constructs "{t}" here '
                f"but the NATIVE_PLANE annotation at "
                f"{_site(np_path, np_line)} does not record it — "
                f"missing annotation (the inventory under-reports "
                f"the native plane)"))
    return findings
