"""Runtime lock-discipline checker.

The static pass (``raylint``) sees what the source *says*; this module
watches what the process *does*. When installed it wraps the
``threading.Lock`` / ``threading.RLock`` factories so every lock
created afterwards records, per thread, the stack of locks currently
held, and flags two families of hazard as they happen:

- **blocking-under-lock** — a thread calls a known blocking primitive
  (``time.sleep``, ``queue.Queue.get``/``put`` without immediate
  semantics, ``threading.Event.wait``, ``socket.recv``) while holding
  a traced lock;
- **lock-order-inversion** — lock *B* is acquired while *A* is held
  somewhere, and elsewhere *A* is acquired while *B* is held. Each
  acquisition adds held→new edges to a global order graph; an edge
  whose reverse is already present is a potential deadlock cycle.

Violations are recorded (not raised) so a test run completes and the
full report surfaces at teardown. The tier-1 suite arms this via an
autouse fixture in ``tests/conftest.py`` when ``RAY_TPU_LOCKTRACE=1``:

    RAY_TPU_LOCKTRACE=1 pytest tests/ -q -m 'not slow'

``threading.Condition.wait`` *releases* its lock while waiting, so a
condition-variable wait under its own lock is not flagged — only waits
under *other* traced locks are.

**Cross-process merge.** A lock-order inversion split across processes
(the driver nests A->B, a daemon nests B->A over the same code paths)
is invisible to any single process's graph. Lock names are keyed by
*creation site* (``Lock@file:line``), which is stable across processes
running the same code, so per-process graphs are mergeable: set
``RAY_TPU_LOCKTRACE_DIR=<dir>`` and call
``maybe_install_from_env()`` early in each process (the worker and
daemon mains do) — every process dumps its order graph to
``<dir>/lockgraph-<pid>.json`` at exit, and ``merge_graphs(dir)``
reports edges whose reverse only exists in *another* process. This is
the runtime twin of the static ``xp-lock-order-inversion`` pass: the
static side sees call chains it can resolve, this side sees whatever
actually ran.
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import queue
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple, Union

# Violations are only REPORTED when the offending call site lives in
# this repo (package root's parent, which also covers tests/): stdlib
# and third-party internals (ThreadPoolExecutor, logging, jax) hold
# their own locks around their own waits by design, and flagging them
# would bury the signal this checker exists for — OUR lock discipline.
_SCOPE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

__all__ = [
    "install", "uninstall", "is_installed", "violations",
    "clear_violations", "report", "TracedLock",
    "dump_graph", "merge_graphs", "merged_report",
    "maybe_install_from_env",
]

_STATE_LOCK = threading.Lock()  # raylint: disable=lock-order-inversion -- tracer-internal; never held across user code
_installed = False
_violations: List["Violation"] = []
# Directed lock-order edges: (name_a, name_b) means "b acquired while
# a held". Seeded with the site that first created each edge so the
# inversion report can show BOTH sides.
_order_edges: Dict[Tuple[str, str], str] = {}
_seen_keys: Set[Tuple[str, ...]] = set()

# Per-thread stack of (lock_name, site) currently held. threading.local
# is per-thread by construction, no locking needed.
_tls = threading.local()


def _held() -> List[Tuple[str, str]]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


@dataclass
class Violation:
    kind: str                 # blocking-under-lock | lock-order-inversion
    thread: str
    detail: str
    site: str                 # "file:line" of the offending call
    held: Tuple[str, ...] = field(default_factory=tuple)

    def render(self) -> str:
        held = f" [held: {', '.join(self.held)}]" if self.held else ""
        return (f"{self.site}: {self.kind} ({self.thread}): "
                f"{self.detail}{held}")


def _site(depth_skip: int = 0) -> str:
    """file:line of the first frame outside this module (and outside
    threading/queue internals)."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if (__file__ in fn or fn.endswith("threading.py")
                or fn.endswith("queue.py")):
            continue
        return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _record(kind: str, detail: str,
            held: Optional[List[Tuple[str, str]]] = None) -> None:
    site = _site()
    if not site.startswith(_SCOPE):
        return
    v = Violation(kind=kind, thread=threading.current_thread().name,
                  detail=detail, site=site,
                  held=tuple(n for n, _ in (held or [])))
    key = (v.kind, v.site, v.detail)
    with _STATE_LOCK:
        if key in _seen_keys:  # dedupe hot loops
            return
        _seen_keys.add(key)
        _violations.append(v)


def _name_lock(obj: object) -> str:
    """Stable human name: creation site of the traced lock."""
    return getattr(obj, "_lt_name", f"lock@{id(obj):#x}")


class TracedLock:
    """Proxy around a real Lock/RLock that maintains the per-thread
    held stack and the global order graph."""

    def __init__(self, factory, kind: str):
        self._inner = factory()
        self._kind = kind
        self._lt_name = f"{kind}@{_site()}"

    # -- order / held-stack bookkeeping ------------------------------

    def _on_acquired(self) -> None:
        held = _held()
        if self._kind == "RLock" and any(
                n == self._lt_name for n, _ in held):
            held.append((self._lt_name, "reentrant"))
            return
        site = _site()
        with _STATE_LOCK:
            for held_name, _ in held:
                if held_name == self._lt_name:
                    continue
                edge = (held_name, self._lt_name)
                rev = (self._lt_name, held_name)
                if edge not in _order_edges:
                    _order_edges[edge] = site
                other = _order_edges.get(rev)
                vkey = ("lock-order-inversion", held_name,
                        self._lt_name)
                if (other is not None and site.startswith(_SCOPE)
                        and vkey not in _seen_keys):
                    _seen_keys.add(vkey)
                    _violations.append(Violation(
                        kind="lock-order-inversion",
                        thread=threading.current_thread().name,
                        detail=(f"{self._lt_name} acquired while "
                                f"{held_name} held, but the reverse "
                                f"order was taken at {other}"),
                        site=site,
                        held=tuple(n for n, _ in held)))
        held.append((self._lt_name, site))

    def _on_released(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self._lt_name:
                del held[i]
                return

    # -- Lock protocol ----------------------------------------------

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._on_acquired()
        return got

    def release(self):
        self._inner.release()
        self._on_released()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # Condition() probes the lock for these; the proxy always has
    # them, so they must also work when the inner lock is a plain
    # Lock (Condition's own fallback is release()/acquire()).
    def _acquire_restore(self, state):
        inner = getattr(self._inner, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._inner.acquire()
        self._on_acquired()

    def _release_save(self):
        inner = getattr(self._inner, "_release_save", None)
        if inner is not None:
            state = inner()
        else:
            self._inner.release()
            state = None
        self._on_released()
        return state

    def _is_owned(self):
        inner = getattr(self._inner, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<TracedLock {self._lt_name}>"


def _blocking_hook(label: str, is_blocking):
    """Wrap a blocking callable: before delegating, flag if any traced
    lock is held by this thread (unless the call is non-blocking)."""
    def deco(orig):
        @functools.wraps(orig)
        def wrapper(*args, **kwargs):
            if is_blocking(args, kwargs):
                held = _held()
                # Only OUR calls count: when the direct caller is the
                # stdlib (e.g. Thread.start()'s bootstrap Event.wait,
                # Timer.start()), the wait is the library's own
                # discipline — _site() would mis-attribute it to the
                # nearest in-repo frame and report a phantom hazard.
                if held and sys._getframe(1).f_code.co_filename.startswith(
                        _SCOPE):
                    _record("blocking-under-lock",
                            f"{label} while holding a traced lock",
                            held)
            return orig(*args, **kwargs)
        return wrapper
    return deco


def _sleep_blocks(args, kwargs) -> bool:
    return bool(args) and (args[0] or 0) > 0


def _wait_blocks(args, kwargs) -> bool:
    # Event.wait(0) / wait(timeout=0) is a poll, not a block.
    t = kwargs.get("timeout", args[1] if len(args) > 1 else None)
    return t is None or t > 0


def _queue_blocks(args, kwargs) -> bool:
    # Queue.get(block=False) / get_nowait() don't block.
    block = kwargs.get("block", args[1] if len(args) > 1 else True)
    return bool(block)


_originals: Dict[str, object] = {}


def install() -> None:
    """Patch the lock factories + blocking primitives. Idempotent."""
    global _installed
    with _STATE_LOCK:
        if _installed:
            return
        _installed = True
    _originals["Lock"] = threading.Lock
    _originals["RLock"] = threading.RLock
    _originals["sleep"] = time.sleep
    _originals["Event.wait"] = threading.Event.wait
    _originals["Queue.get"] = queue.Queue.get
    _originals["Queue.put"] = queue.Queue.put

    real_lock, real_rlock = threading.Lock, threading.RLock
    threading.Lock = lambda: TracedLock(real_lock, "Lock")
    threading.RLock = lambda: TracedLock(real_rlock, "RLock")
    time.sleep = _blocking_hook("time.sleep", _sleep_blocks)(
        _originals["sleep"])
    threading.Event.wait = _blocking_hook(
        "Event.wait", _wait_blocks)(_originals["Event.wait"])
    queue.Queue.get = _blocking_hook(
        "Queue.get", _queue_blocks)(_originals["Queue.get"])
    queue.Queue.put = _blocking_hook(
        "Queue.put", _queue_blocks)(_originals["Queue.put"])


def uninstall() -> None:
    """Restore the patched factories. Locks created while installed
    keep tracing (they are TracedLock instances) but new ones do not."""
    global _installed
    with _STATE_LOCK:
        if not _installed:
            return
        _installed = False
    threading.Lock = _originals["Lock"]
    threading.RLock = _originals["RLock"]
    time.sleep = _originals["sleep"]
    threading.Event.wait = _originals["Event.wait"]
    queue.Queue.get = _originals["Queue.get"]
    queue.Queue.put = _originals["Queue.put"]


def is_installed() -> bool:
    return _installed


def violations() -> List[Violation]:
    with _STATE_LOCK:
        return list(_violations)


def clear_violations() -> None:
    with _STATE_LOCK:
        _violations.clear()
        _seen_keys.clear()
        _order_edges.clear()


def report() -> str:
    vs = violations()
    if not vs:
        return "locktrace: no violations"
    lines = [f"locktrace: {len(vs)} violation(s)"]
    lines += [f"  {v.render()}" for v in vs]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Cross-process merge
# ---------------------------------------------------------------------------

_ENV_DIR = "RAY_TPU_LOCKTRACE_DIR"


def dump_graph(path: Optional[str] = None) -> Optional[str]:
    """Write this process's lock-order graph to ``path`` (default:
    ``$RAY_TPU_LOCKTRACE_DIR/lockgraph-<pid>.json``). Returns the path
    written, or None when there is nowhere to write."""
    if path is None:
        d = os.environ.get(_ENV_DIR)
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"lockgraph-{os.getpid()}.json")
    with _STATE_LOCK:
        edges = [[a, b, site] for (a, b), site in _order_edges.items()]
    payload = {
        "pid": os.getpid(),
        "label": os.path.basename(sys.argv[0]) or "python",
        "edges": edges,
    }
    # Atomic write: a merger scanning the directory mid-dump must see
    # either nothing or a complete graph, never a truncated one.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def merge_graphs(
        source: Union[str, Iterable[str]]) -> List[Violation]:
    """Merge per-process graph dumps and report lock-order inversions
    that only exist ACROSS processes: edge (a, b) in one dump, (b, a)
    in another, and no single dump holding both directions (those were
    already reported live by the process that saw them). ``source`` is
    a dump directory or an iterable of dump paths."""
    if isinstance(source, str):
        paths = sorted(
            os.path.join(source, fn) for fn in os.listdir(source)
            if fn.startswith("lockgraph-") and fn.endswith(".json"))
    else:
        paths = list(source)
    graphs = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        edges = {(a, b): site for a, b, site in data.get("edges", [])}
        graphs.append((data.get("pid"), data.get("label", "?"), edges))

    out: List[Violation] = []
    seen: Set[Tuple[str, str]] = set()
    for i, (pid_a, label_a, edges_a) in enumerate(graphs):
        for (a, b), site_ab in edges_a.items():
            if (b, a) in edges_a:
                continue  # intra-process: reported live already
            for pid_b, label_b, edges_b in graphs[i + 1:]:
                site_ba = edges_b.get((b, a))
                if site_ba is None or (a, b) in edges_b:
                    continue
                # The inversion only matters when OUR code took at
                # least one side; a pair living entirely in stdlib /
                # third-party frames is their discipline, not ours.
                if not (site_ab.startswith(_SCOPE)
                        or site_ba.startswith(_SCOPE)):
                    continue
                key = (min(a, b), max(a, b))
                if key in seen:
                    continue
                seen.add(key)
                out.append(Violation(
                    kind="lock-order-inversion",
                    thread=f"pid {pid_a}({label_a}) vs "
                           f"pid {pid_b}({label_b})",
                    detail=(f"{b} acquired while {a} held at {site_ab} "
                            f"(pid {pid_a}), but the reverse order was "
                            f"taken at {site_ba} (pid {pid_b})"),
                    site=site_ab,
                    held=(a,)))
    return out


def merged_report(source: Union[str, Iterable[str]]) -> str:
    vs = merge_graphs(source)
    if not vs:
        return "locktrace: no cross-process violations"
    lines = [f"locktrace: {len(vs)} cross-process violation(s)"]
    lines += [f"  {v.render()}" for v in vs]
    return "\n".join(lines)


def maybe_install_from_env() -> bool:
    """Arm tracing + an at-exit graph dump iff ``RAY_TPU_LOCKTRACE_DIR``
    is set. Called from the worker and daemon mains so every process in
    a traced cluster contributes a graph; a no-op otherwise (plain
    ``RAY_TPU_LOCKTRACE=1`` single-process behavior is unchanged)."""
    if not os.environ.get(_ENV_DIR):
        return False
    install()
    atexit.register(dump_graph)
    return True
