"""Developer tooling for the ray_tpu runtime itself.

Two checkers police the distributed-runtime invariants that the
round-5 advisor audit found violated by hand (ADVICE.md) and that CI
must catch mechanically at production scale (the reference codebase
leans on TSan builds and ``ray.util.inspect_serializability`` the same
way):

- :mod:`ray_tpu.devtools.raylint` — an AST static-analysis pass with a
  rule registry (blocking-under-lock, unguarded-handle-teardown,
  state-roundtrip-asymmetry, naked-get-in-actor, unserializable-capture,
  lock-order-inversion) run in tier-1 over the whole tree and exposed
  as the ``ray-tpu raylint`` CLI subcommand.
- :mod:`ray_tpu.devtools.locktrace` — a runtime lock-discipline
  checker that wraps ``threading.Lock``/``RLock`` to record per-thread
  held-lock sets during a test run (``RAY_TPU_LOCKTRACE=1``).
"""

from . import locktrace, raylint  # noqa: F401

__all__ = ["raylint", "locktrace"]
