"""ray_tpu — a TPU-native distributed computing framework.

Public API surface mirrors the reference's (reference:
python/ray/__init__.py — init/shutdown, @remote, get/put/wait/cancel/kill,
actors, placement groups, runtime context), re-designed TPU-first: the
scheduler is ICI-topology-aware, collectives are XLA collectives over
meshes (`ray_tpu.parallel`), and the AI libraries (`data`, `train`,
`serve`, `tune`, `rl`) run SPMD programs on TPU slices.
"""

from __future__ import annotations

import inspect as _inspect
from typing import Any, Dict, List, Optional, Sequence, Union

from ._version import __version__
from .util import jax_compat as _jax_compat

_jax_compat.install()

from .core import runtime as _runtime
from .core.actor import (ActorClass, ActorHandle, exit_actor,
                         get_actor, method)
from .core.exceptions import (
    ActorDiedError,
    ActorError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
)
from .core.graphable import graphable, is_graphable
from .core.object_ref import ObjectRef
from .core.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from .core.remote_function import RemoteFunction
from .core.runtime import ObjectRefGenerator, RuntimeContext
from .core.task import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SliceAffinitySchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "__version__", "init", "shutdown", "is_initialized", "remote", "get",
    "put", "wait", "cancel", "kill", "get_actor", "exit_actor", "method",
    "graphable", "is_graphable",
    "ObjectRef",
    "ObjectRefGenerator", "ActorClass", "ActorHandle", "RemoteFunction",
    "PlacementGroup", "placement_group", "remove_placement_group",
    "get_runtime_context", "cluster_resources", "available_resources",
    "timeline", "nodes", "method",
    "NodeAffinitySchedulingStrategy", "PlacementGroupSchedulingStrategy",
    "SliceAffinitySchedulingStrategy", "SpreadSchedulingStrategy",
    "RayTpuError", "TaskError", "ActorError", "ActorDiedError",
    "ObjectLostError", "TaskCancelledError", "GetTimeoutError",
]


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         num_worker_procs: int = 0,
         namespace: Optional[str] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         ignore_reinit_error: bool = True, **_compat) -> None:
    """Start (or connect to) the runtime.

    address="tpu://host:port" enters CLIENT MODE: this process becomes a
    remote driver against a ClientServer-hosted runtime (reference:
    ray.init("ray://...") → python/ray/util/client/). All other options
    start a local runtime.

    num_worker_procs > 0 adds an out-of-process execution plane: that
    many spawned worker processes (true parallelism, crash isolation)
    sharing the zero-copy shm object store (core/worker_proc.py).

    Reference parity: ray.init (python/ray/_private/worker.py:1227).
    """
    if address is not None and (address.startswith("tpu://")
                                or address.startswith("ray://")):
        from . import client as _client_mod

        if _client_mod.get_client() is not None:
            if ignore_reinit_error:
                return
            raise RuntimeError("already connected in client mode")
        _client_mod.connect(address, namespace=namespace)
        return
    if _runtime.is_initialized():
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu is already initialized")
    # A bare "host:port" address joins a daemon-backed cluster as a
    # driver (reference: ray.init(address="host:port") joining a
    # `ray start` cluster); node daemons appear as schedulable nodes.
    _runtime.init_runtime(
        num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
        num_worker_procs=num_worker_procs,
        cluster_address=address,
        advertise_host=_compat.get("advertise_host", "127.0.0.1"),
        _system_config=_system_config)
    if namespace:
        _runtime.global_runtime().namespace = namespace


def shutdown() -> None:
    from . import client as _client_mod

    _client_mod.disconnect()
    _runtime.shutdown_runtime()


def is_initialized() -> bool:
    from . import client as _client_mod

    return (_runtime.is_initialized()
            or _client_mod.get_client() is not None)


def _client():
    """Active client context, or None (client-mode routing hook)."""
    from . import client as _client_mod

    return _client_mod.get_client()


# ---------------------------------------------------------------------------
# @remote
# ---------------------------------------------------------------------------

def remote(*args, **options):
    """Decorate a function → RemoteFunction, or a class → ActorClass.

    Supports both ``@remote`` and ``@remote(num_tpus=1, ...)`` forms
    (reference: python/ray/__init__.py remote / worker.py make_decorator).
    """
    if len(args) == 1 and not options and (
            callable(args[0]) or _inspect.isclass(args[0])):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only")

    def decorator(obj):
        return _make_remote(obj, options)

    return decorator


def _make_remote(obj, options):
    if _inspect.isclass(obj):
        return ActorClass(obj, options)
    if callable(obj):
        return RemoteFunction(obj, options)
    raise TypeError(f"@remote target must be function or class: {obj!r}")


# @method lives in core.actor (imported above): per-method defaults for
# num_returns / concurrency_group, consumed at submit time.


# ---------------------------------------------------------------------------
# Object API
# ---------------------------------------------------------------------------

def put(value: Any) -> ObjectRef:
    c = _client()
    if c is not None:
        return c.put(value)
    return _runtime.global_runtime().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    c = _client()
    if c is not None:
        from .client.common import ClientObjectRef

        if isinstance(refs, ClientObjectRef):
            return c.get(refs, timeout)
        return c.get(list(refs), timeout)
    rt = _runtime.global_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout)[0]
    if isinstance(refs, ObjectRefGenerator):
        raise TypeError(
            "Pass individual refs from the generator to get(), not the "
            "generator itself.")
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects ObjectRef or list, got {type(refs)}")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list items must be ObjectRef: {type(r)}")
    return rt.get(list(refs), timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns={num_returns} exceeds {len(refs)} provided refs")
    c = _client()
    if c is not None:
        return c.wait(list(refs), num_returns, timeout)
    return _runtime.global_runtime().wait(
        list(refs), num_returns, timeout, fetch_local)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    c = _client()
    if c is not None:
        c.cancel(ref, force=force)
        return
    _runtime.global_runtime().cancel(ref, force=force)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    c = _client()
    if c is not None:
        from .client.client import ClientActorHandle

        if isinstance(actor, ClientActorHandle):
            c.kill_actor(actor._actor_id, no_restart=no_restart)
            return
    _runtime.global_runtime().kill_actor(
        actor._actor_id, no_restart=no_restart)


# ---------------------------------------------------------------------------
# Introspection
# ---------------------------------------------------------------------------

def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()


def cluster_resources() -> Dict[str, float]:
    c = _client()
    if c is not None:
        return c.cluster_resources()
    return _runtime.global_runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    c = _client()
    if c is not None:
        return c.available_resources()
    return _runtime.global_runtime().available_resources()


def nodes() -> List[dict]:
    rt = _runtime.global_runtime()
    return [
        {
            "NodeID": n.node_id, "Alive": n.alive,
            "Resources": n.total.to_dict(), "Labels": dict(n.labels),
        }
        for n in rt.scheduler.nodes()
    ]


def timeline(filename: Optional[str] = None):
    """Chrome-trace dump (reference: ray timeline CLI)."""
    events = _runtime.global_runtime().timeline()
    if filename:
        import json
        with open(filename, "w") as f:
            json.dump(events, f)
        return None
    return events
