"""Workflow public API.

Capability-equivalent to the reference's workflow API (reference:
python/ray/workflow/api.py — run :120, run_async :174, resume :251,
get_output, get_status, list_all, delete, wait_for_event): durable,
crash-resumable DAG execution. A workflow is a ray_tpu DAG built with
`.bind()`; every step result is checkpointed before dependents run, so
`resume()` after a crash replays completed steps from storage.
"""

from __future__ import annotations

import threading
import uuid

import cloudpickle as pickle  # locally-defined DAG fns must persist
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from ..dag.node import DAGNode
from .event import EventListener, TimerListener
from .executor import WorkflowCanceled, WorkflowExecutor
from .storage import WorkflowStorage

# Workflow status values (parity with the reference's WorkflowStatus).
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
RESUMABLE = "RESUMABLE"
CANCELED = "CANCELED"

_default_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()
# Workflow ids being driven by THIS process (guards resume_all's
# stale-RUNNING heuristic and the cancel-vs-start status race; the
# status file itself has no compare-and-swap).
_active_local: set = set()
_status_lock = threading.Lock()


def init(storage_root: Optional[str] = None) -> None:
    """Configure workflow storage (reference: workflow.init)."""
    global _default_storage
    with _lock:
        _default_storage = WorkflowStorage(storage_root)


def _storage() -> WorkflowStorage:
    global _default_storage
    with _lock:
        if _default_storage is None:
            _default_storage = WorkflowStorage()
        return _default_storage


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; returns the final result."""
    store = _storage()
    wid = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    try:
        store.save_dag(wid, pickle.dumps((dag, args)))
    except Exception:  # noqa: BLE001 — unpicklable DAGs still run
        pass
    # Check-and-set under the same lock cancel() takes, so a cancel
    # landing between the check and the RUNNING write cannot be erased
    # (same-process; the file store has no cross-process CAS).
    with _status_lock:
        if store.get_status(wid) == CANCELED:
            # A canceled id stays canceled until explicitly delete()d.
            raise WorkflowCanceled(wid)
        store.set_status(wid, RUNNING)
        _active_local.add(wid)
    try:
        result = WorkflowExecutor(store, wid).execute(dag, *args)
        with _status_lock:
            if store.get_status(wid) == CANCELED:
                # cancel() landed while the final step was executing:
                # the cancellation wins; no output is recorded.
                raise WorkflowCanceled(wid)
            store.save_output(wid, result)
            store.set_status(wid, SUCCESSFUL)
        return result
    except WorkflowCanceled:
        # cancel() already set CANCELED; don't downgrade to RESUMABLE.
        raise
    except Exception as e:
        # Reference semantics: application errors (a task raised) are
        # FAILED; infrastructure interruptions are RESUMABLE.
        from ..core.exceptions import TaskError

        store.set_status(
            wid, FAILED if isinstance(e, TaskError) else RESUMABLE)
        raise
    finally:
        with _status_lock:
            _active_local.discard(wid)


def run_async(dag: DAGNode, *args,
              workflow_id: Optional[str] = None) -> Future:
    fut: Future = Future()

    def target():
        try:
            fut.set_result(run(dag, *args, workflow_id=workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=target, daemon=True).start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a failed/crashed workflow; completed steps replay from
    storage (reference: workflow/api.py resume)."""
    store = _storage()
    if store.has_output(workflow_id):
        return store.load_output(workflow_id)
    blob = store.load_dag(workflow_id)
    if blob is None:
        raise ValueError(f"workflow {workflow_id!r} not found or its "
                         "DAG was not persisted")
    dag, args = pickle.loads(blob)
    return run(dag, *args, workflow_id=workflow_id)


def get_status(workflow_id: str) -> Optional[str]:
    return _storage().get_status(workflow_id)


def get_output(workflow_id: str) -> Any:
    store = _storage()
    if not store.has_output(workflow_id):
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={store.get_status(workflow_id)})")
    return store.load_output(workflow_id)


def list_all(status_filter: Optional[str] = None
             ) -> List[Tuple[str, str]]:
    out = _storage().list_workflows()
    if status_filter:
        out = [(w, s) for w, s in out if s == status_filter]
    return out


def delete(workflow_id: str) -> None:
    _storage().delete_workflow(workflow_id)


def wait_for_event(listener: EventListener, timeout: Optional[float] = None
                   ) -> Any:
    """Block a workflow step on an external event (reference:
    workflow/api.py wait_for_event + event_listener.py)."""
    return listener.poll_for_event(timeout)


def resume_async(workflow_id: str) -> Future:
    """(reference: workflow/api.py resume_async :271)."""
    fut: Future = Future()

    def target():
        try:
            fut.set_result(resume(workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=target, daemon=True).start()
    return fut


def resume_all(include_failed: bool = False
               ) -> List[Tuple[str, Future]]:
    """Resume every interrupted workflow (reference: workflow/api.py
    resume_all :499). Covers RESUMABLE plus workflows stuck RUNNING
    with no output — a hard crash (kill -9, power loss) never gets to
    write RESUMABLE, so stale-RUNNING is the normal crash signature.
    Only call after confirming no other process is still driving them.
    include_failed adds FAILED (application-error) workflows."""
    store = _storage()
    states = {RESUMABLE}
    if include_failed:
        states.add(FAILED)
    out = []
    with _status_lock:
        active = set(_active_local)
    for wid, status in list_all():
        # RUNNING ids driven by THIS process are live, not stale —
        # resuming them would double-execute their steps.
        stale_running = (status == RUNNING and wid not in active
                         and not store.has_output(wid))
        if status in states or stale_running:
            out.append((wid, resume_async(wid)))
    return out


def get_output_async(workflow_id: str) -> Future:
    """(reference: workflow/api.py get_output_async :350). Waits for a
    RUNNING workflow to finish rather than raising."""
    import time as _time

    fut: Future = Future()

    def target():
        try:
            store = _storage()
            # status None = run_async's thread hasn't written RUNNING
            # yet (save_dag runs first) — give it a startup grace so a
            # get_output_async issued right after run_async waits
            # instead of failing; beyond the grace, None means the id
            # doesn't exist.
            grace_deadline = _time.monotonic() + 5.0
            while not store.has_output(workflow_id):
                status = store.get_status(workflow_id)
                if status == RUNNING:
                    pass
                elif status is None:
                    if _time.monotonic() >= grace_deadline:
                        break
                else:
                    break
                _time.sleep(0.05)
            fut.set_result(get_output(workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=target, daemon=True).start()
    return fut


def cancel(workflow_id: str) -> None:
    """Mark a workflow CANCELED; its executor stops before the next
    step (reference: workflow/api.py cancel :709 — checkpointed state
    is kept, unlike delete). Terminal workflows cannot be canceled."""
    store = _storage()
    with _status_lock:
        status = store.get_status(workflow_id)
        if status is None:
            raise ValueError(f"workflow {workflow_id!r} not found")
        if status in (SUCCESSFUL, CANCELED):
            raise ValueError(
                f"workflow {workflow_id!r} is {status}; cannot cancel")
        store.set_status(workflow_id, CANCELED)


def get_metadata(workflow_id: str) -> Dict[str, Any]:
    """Status + per-step checkpoint info (reference: workflow/api.py
    get_metadata :646)."""
    store = _storage()
    status = store.get_status(workflow_id)
    if status is None:
        raise ValueError(f"workflow {workflow_id!r} not found")
    return {
        "workflow_id": workflow_id,
        "status": status,
        "steps_checkpointed": store.list_steps(workflow_id),
        "has_output": store.has_output(workflow_id),
    }


def sleep(duration: float) -> DAGNode:
    """A bindable step that blocks the workflow for `duration` seconds
    (reference: workflow/api.py sleep :632 — returns a DAG node so the
    timer participates in the durable DAG)."""
    from .. import remote

    @remote
    def _workflow_sleep(d: float) -> float:
        wait_for_event(TimerListener(d))
        return d

    return _workflow_sleep.bind(duration)
