"""Workflow public API.

Capability-equivalent to the reference's workflow API (reference:
python/ray/workflow/api.py — run :120, run_async :174, resume :251,
get_output, get_status, list_all, delete, wait_for_event): durable,
crash-resumable DAG execution. A workflow is a ray_tpu DAG built with
`.bind()`; every step result is checkpointed before dependents run, so
`resume()` after a crash replays completed steps from storage.
"""

from __future__ import annotations

import pickle
import threading
import uuid
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from ..dag.node import DAGNode
from .event import EventListener, TimerListener
from .executor import WorkflowExecutor
from .storage import WorkflowStorage

# Workflow status values (parity with the reference's WorkflowStatus).
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
RESUMABLE = "RESUMABLE"

_default_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()


def init(storage_root: Optional[str] = None) -> None:
    """Configure workflow storage (reference: workflow.init)."""
    global _default_storage
    with _lock:
        _default_storage = WorkflowStorage(storage_root)


def _storage() -> WorkflowStorage:
    global _default_storage
    with _lock:
        if _default_storage is None:
            _default_storage = WorkflowStorage()
        return _default_storage


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; returns the final result."""
    store = _storage()
    wid = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    try:
        store.save_dag(wid, pickle.dumps((dag, args)))
    except Exception:  # noqa: BLE001 — unpicklable DAGs still run
        pass
    store.set_status(wid, RUNNING)
    try:
        result = WorkflowExecutor(store, wid).execute(dag, *args)
    except Exception:
        store.set_status(wid, RESUMABLE)
        raise
    store.save_output(wid, result)
    store.set_status(wid, SUCCESSFUL)
    return result


def run_async(dag: DAGNode, *args,
              workflow_id: Optional[str] = None) -> Future:
    fut: Future = Future()

    def target():
        try:
            fut.set_result(run(dag, *args, workflow_id=workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=target, daemon=True).start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a failed/crashed workflow; completed steps replay from
    storage (reference: workflow/api.py resume)."""
    store = _storage()
    if store.has_output(workflow_id):
        return store.load_output(workflow_id)
    blob = store.load_dag(workflow_id)
    if blob is None:
        raise ValueError(f"workflow {workflow_id!r} not found or its "
                         "DAG was not persisted")
    dag, args = pickle.loads(blob)
    return run(dag, *args, workflow_id=workflow_id)


def get_status(workflow_id: str) -> Optional[str]:
    return _storage().get_status(workflow_id)


def get_output(workflow_id: str) -> Any:
    store = _storage()
    if not store.has_output(workflow_id):
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={store.get_status(workflow_id)})")
    return store.load_output(workflow_id)


def list_all(status_filter: Optional[str] = None
             ) -> List[Tuple[str, str]]:
    out = _storage().list_workflows()
    if status_filter:
        out = [(w, s) for w, s in out if s == status_filter]
    return out


def delete(workflow_id: str) -> None:
    _storage().delete_workflow(workflow_id)


def wait_for_event(listener: EventListener, timeout: Optional[float] = None
                   ) -> Any:
    """Block a workflow step on an external event (reference:
    workflow/api.py wait_for_event + event_listener.py)."""
    return listener.poll_for_event(timeout)
