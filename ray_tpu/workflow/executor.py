"""Workflow executor — durable DAG evaluation.

Capability-equivalent to the reference's executor + state machine
(reference: python/ray/workflow/workflow_executor.py:32 WorkflowExecutor,
workflow_state_from_dag.py — DAG → steps with stable ids, completed
steps skipped on resume): walks a ray_tpu DAG, runs each FunctionNode as
a remote task, checkpoints every step result before its dependents run,
and replays from storage on resume instead of re-executing.

Step keys are content-derived (function qualname + static args + dep
keys), so the same DAG re-built after a crash maps onto the same stored
results.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict

from ..dag.node import DAGNode, FunctionNode, InputNode, MultiOutputNode
from .storage import WorkflowStorage


def _stable_repr(v: Any) -> str:
    try:
        return repr(v)
    except Exception:  # noqa: BLE001
        return f"<{type(v).__name__}>"


def step_key(node: DAGNode, dep_keys: Dict[int, str]) -> str:
    """Deterministic id for a DAG node, stable across processes."""
    if isinstance(node, InputNode):
        return "__input__"
    parts = [type(node).__name__]
    if isinstance(node, FunctionNode):
        fn = node._remote_fn
        target = getattr(fn, "_func", None) or fn
        parts.append(getattr(target, "__qualname__", repr(target)))
    for a in node._bound_args:
        parts.append(dep_keys[id(a)] if isinstance(a, DAGNode)
                     else _stable_repr(a))
    for k in sorted(node._bound_kwargs):
        v = node._bound_kwargs[k]
        parts.append(
            f"{k}={dep_keys[id(v)] if isinstance(v, DAGNode) else _stable_repr(v)}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:20]


class WorkflowCanceled(Exception):
    """Raised inside a run when cancel() flipped the workflow state."""


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage, workflow_id: str):
        self.storage = storage
        self.workflow_id = workflow_id

    def execute(self, dag: DAGNode, *input_args) -> Any:
        import ray_tpu as ray

        keys: Dict[int, str] = {}
        results: Dict[int, Any] = {}

        def run(node: DAGNode) -> Any:
            nid = id(node)
            if nid in results:
                return results[nid]
            for dep in node._deps():
                run(dep)
            key = step_key(node, keys)
            keys[nid] = key

            if isinstance(node, InputNode):
                value = (input_args[0]
                         if len(input_args) == 1 else input_args)
            elif isinstance(node, MultiOutputNode):
                value = [results[id(o)] for o in node._bound_args]
            elif self.storage.has_step(self.workflow_id, key):
                value = self.storage.load_step(self.workflow_id, key)
            elif isinstance(node, FunctionNode):
                # Cancellation gate: checked before every fresh step
                # (reference: cancel marks the state; checkpointed
                # steps stay for a later resume).
                if self.storage.get_status(self.workflow_id) == "CANCELED":
                    raise WorkflowCanceled(self.workflow_id)
                args = tuple(
                    results[id(a)] if isinstance(a, DAGNode) else a
                    for a in node._bound_args)
                kwargs = {
                    k: results[id(v)] if isinstance(v, DAGNode) else v
                    for k, v in node._bound_kwargs.items()}
                # Checkpoint BEFORE dependents: the durability contract.
                value = ray.get(node._remote_fn.remote(*args, **kwargs))
                self.storage.save_step(self.workflow_id, key, value)
            else:
                raise TypeError(
                    f"workflows support function DAGs; got "
                    f"{type(node).__name__} (actor nodes are not "
                    f"durable)")
            results[nid] = value
            return value

        return run(dag)
