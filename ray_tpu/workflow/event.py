"""Workflow event listeners.

Capability-equivalent to the reference's event system (reference:
python/ray/workflow/event_listener.py EventListener ABC + TimerListener,
http_event_provider.py): a listener blocks a workflow step until an
external event arrives.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional


class EventListener:
    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError


class TimerListener(EventListener):
    """Fires after a duration (reference: event_listener.py TimerListener)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        time.sleep(self.seconds)
        return time.time()


class QueueEventProvider(EventListener):
    """In-process event queue — post() from anywhere unblocks the step
    (stand-in for the reference's HTTP event provider)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._events: list = []

    def post(self, event: Any) -> None:
        with self._cv:
            self._events.append(event)
            self._cv.notify_all()

    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._events:
                remain = (None if deadline is None
                          else deadline - time.monotonic())
                if remain is not None and remain <= 0:
                    raise TimeoutError("no event before timeout")
                self._cv.wait(remain)
            return self._events.pop(0)
