"""Workflow event listeners.

Capability-equivalent to the reference's event system (reference:
python/ray/workflow/event_listener.py EventListener ABC + TimerListener,
http_event_provider.py): a listener blocks a workflow step until an
external event arrives.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional


class EventListener:
    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError


class TimerListener(EventListener):
    """Fires after a duration (reference: event_listener.py TimerListener)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        time.sleep(self.seconds)
        return time.time()


class QueueEventProvider(EventListener):
    """In-process event queue — post() from anywhere unblocks the step
    (stand-in for the reference's HTTP event provider)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._events: list = []

    def post(self, event: Any) -> None:
        with self._cv:
            self._events.append(event)
            self._cv.notify_all()

    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._events:
                remain = (None if deadline is None
                          else deadline - time.monotonic())
                if remain is not None and remain <= 0:
                    raise TimeoutError("no event before timeout")
                self._cv.wait(remain)
            return self._events.pop(0)


class HTTPEventProvider:
    """HTTP ingress for workflow events (reference:
    workflow/http_event_provider.py — workflows block on
    `listener(key)` until `POST /event/<key>` arrives with a JSON
    body). One provider serves many keys; each key is an independent
    event queue."""

    MAX_KEYS = 1024  # unauthenticated endpoint: bound key growth

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._queues: dict = {}
        self._lock = threading.Lock()
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._loop = None
        self._runner = None
        self._serve_error: Optional[BaseException] = None

    def _queue(self, key: str,
               create: bool = True) -> Optional[QueueEventProvider]:
        with self._lock:
            q = self._queues.get(key)
            if q is None and create:
                if len(self._queues) >= self.MAX_KEYS:
                    return None
                q = self._queues[key] = QueueEventProvider()
            return q

    def listener(self, key: str) -> EventListener:
        """The EventListener a workflow step blocks on for `key`."""
        q = self._queue(key)
        if q is None:
            raise RuntimeError(f"event key limit ({self.MAX_KEYS}) hit")
        return q

    def remove_listener(self, key: str) -> None:
        """Drop a consumed key's queue (long-lived providers should
        evict keys their workflows have finished with)."""
        with self._lock:
            self._queues.pop(key, None)

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HTTPEventProvider":
        if self._thread is not None:
            return self
        self._serve_error = None
        self._started.clear()
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="workflow-events")
        self._thread.start()
        self._started.wait(10)
        if self._serve_error is not None:
            # _serve signals failures immediately (no 10s stall).
            self._thread = None
            raise RuntimeError(
                "event provider failed to start") from self._serve_error
        if not self._started.is_set():
            # Setup genuinely slow: KEEP the thread reference so stop()
            # can still join it — a retry must not double-bind.
            raise RuntimeError(
                "event provider did not start within 10s; call stop() "
                "before retrying")
        return self

    def stop(self) -> None:
        import asyncio

        if self._loop is not None:
            loop = self._loop

            async def _shutdown():
                await self._runner.cleanup()
                loop.stop()

            asyncio.run_coroutine_threadsafe(_shutdown(), loop)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._loop is not None:
            self._loop.close()  # release the selector fd
            self._loop = None
        self._runner = None  # raylint: disable=unguarded-handle-teardown -- stop() awaits runner.cleanup() on the loop and joins the server thread before clearing
        self._started.clear()

    def _serve(self) -> None:
        import asyncio
        import json

        from aiohttp import web

        async def post_event(request: "web.Request"):
            key = request.match_info["key"]
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                payload = (await request.read()).decode()
            q = self._queue(key)
            if q is None:
                return web.json_response(
                    {"error": "event key limit reached"}, status=429)
            q.post(payload)
            return web.json_response({"status": "posted", "key": key})

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            app = web.Application()
            app.router.add_post("/event/{key}", post_event)
            self._runner = web.AppRunner(app)
            loop.run_until_complete(self._runner.setup())
            site = web.TCPSite(self._runner, self.host, self.port)
            loop.run_until_complete(site.start())
            # Public API for the bound address (port=0 resolution).
            self.port = self._runner.addresses[0][1]
        except BaseException as e:  # noqa: BLE001
            self._serve_error = e
            loop.close()
            self._loop = None
            self._started.set()  # wake the waiter NOW with the error
            return
        self._started.set()
        loop.run_forever()
