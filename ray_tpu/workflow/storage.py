"""Durable workflow storage.

Capability-equivalent to the reference's workflow storage layer
(reference: python/ray/workflow/workflow_storage.py — step-result
persistence keyed by workflow_id + step id, workflow status/metadata,
`ray.storage` filesystem backends): a directory tree

    <root>/<workflow_id>/status.json
    <root>/<workflow_id>/steps/<step_key>.pkl

Results are written atomically (tmp + rename) so a crash mid-write never
leaves a readable-but-torn step result.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional, Tuple

_DEFAULT_ROOT = os.path.join(
    tempfile.gettempdir(), "ray_tpu_workflows")


class WorkflowStorage:
    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get(
            "RAY_TPU_WORKFLOW_ROOT", _DEFAULT_ROOT)
        os.makedirs(self.root, exist_ok=True)

    # -- workflow-level ----------------------------------------------
    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.root, workflow_id)

    def list_workflows(self) -> List[Tuple[str, str]]:
        out = []
        if not os.path.isdir(self.root):
            return out
        for wid in sorted(os.listdir(self.root)):
            status = self.get_status(wid)
            if status is not None:
                out.append((wid, status))
        return out

    def set_status(self, workflow_id: str, status: str,
                   extra: Optional[Dict[str, Any]] = None) -> None:
        d = self._wf_dir(workflow_id)
        os.makedirs(d, exist_ok=True)
        self._atomic_write(
            os.path.join(d, "status.json"),
            json.dumps({"status": status, **(extra or {})}).encode())

    def get_status(self, workflow_id: str) -> Optional[str]:
        p = os.path.join(self._wf_dir(workflow_id), "status.json")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f).get("status")

    def delete_workflow(self, workflow_id: str) -> None:
        import shutil
        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    # -- step-level ---------------------------------------------------
    def _step_path(self, workflow_id: str, step_key: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps",
                            f"{step_key}.pkl")

    def has_step(self, workflow_id: str, step_key: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, step_key))

    def list_steps(self, workflow_id: str) -> List[str]:
        """Checkpointed step keys (reference: get_metadata surface).
        The workflow-level output record is not a step."""
        d = os.path.join(self._wf_dir(workflow_id), "steps")
        if not os.path.isdir(d):
            return []
        return sorted(f[:-4] for f in os.listdir(d)
                      if f.endswith(".pkl") and f != "__output__.pkl")

    def save_step(self, workflow_id: str, step_key: str,
                  result: Any) -> None:
        p = self._step_path(workflow_id, step_key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        self._atomic_write(p, pickle.dumps(result))

    def load_step(self, workflow_id: str, step_key: str) -> Any:
        with open(self._step_path(workflow_id, step_key), "rb") as f:
            return pickle.load(f)

    def save_output(self, workflow_id: str, result: Any) -> None:
        self.save_step(workflow_id, "__output__", result)

    def load_output(self, workflow_id: str) -> Any:
        return self.load_step(workflow_id, "__output__")

    def has_output(self, workflow_id: str) -> bool:
        return self.has_step(workflow_id, "__output__")

    # -- dag persistence (for resume) ---------------------------------
    def save_dag(self, workflow_id: str, dag_bytes: bytes) -> None:
        d = self._wf_dir(workflow_id)
        os.makedirs(d, exist_ok=True)
        self._atomic_write(os.path.join(d, "dag.pkl"), dag_bytes)

    def load_dag(self, workflow_id: str) -> Optional[bytes]:
        p = os.path.join(self._wf_dir(workflow_id), "dag.pkl")
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
