"""ray_tpu.workflow — durable, crash-resumable DAG execution.

Capability-equivalent to the reference's Workflow library (reference:
python/ray/workflow/ — SURVEY.md §2.3 Workflow row: every step's result
checkpointed to storage, resumable, events, subworkflows via nested
DAGs).
"""

from .api import (
    CANCELED,
    FAILED,
    RESUMABLE,
    RUNNING,
    SUCCESSFUL,
    cancel,
    delete,
    get_metadata,
    get_output,
    get_output_async,
    get_status,
    init,
    list_all,
    resume,
    resume_all,
    resume_async,
    run,
    run_async,
    sleep,
    wait_for_event,
)
from .event import (EventListener, HTTPEventProvider,
                    QueueEventProvider, TimerListener)
from .storage import WorkflowStorage

__all__ = [
    "run", "run_async", "resume", "resume_async", "resume_all",
    "get_output", "get_output_async", "get_status", "get_metadata",
    "list_all", "delete", "cancel", "sleep", "init", "wait_for_event",
    "EventListener", "TimerListener", "HTTPEventProvider",
    "QueueEventProvider", "WorkflowStorage", "RUNNING", "SUCCESSFUL",
    "FAILED", "RESUMABLE", "CANCELED",
]
