"""ray_tpu.workflow — durable, crash-resumable DAG execution.

Capability-equivalent to the reference's Workflow library (reference:
python/ray/workflow/ — SURVEY.md §2.3 Workflow row: every step's result
checkpointed to storage, resumable, events, subworkflows via nested
DAGs).
"""

from .api import (
    FAILED,
    RESUMABLE,
    RUNNING,
    SUCCESSFUL,
    delete,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
    wait_for_event,
)
from .event import (EventListener, HTTPEventProvider,
                    QueueEventProvider, TimerListener)
from .storage import WorkflowStorage

__all__ = [
    "run", "run_async", "resume", "get_output", "get_status", "list_all",
    "delete", "init", "wait_for_event", "EventListener", "TimerListener",
    "HTTPEventProvider",
    "QueueEventProvider", "WorkflowStorage", "RUNNING", "SUCCESSFUL",
    "FAILED", "RESUMABLE",
]
