"""ctypes binding for the C++ shared-memory object store.

Capability-equivalent to the reference's PlasmaClient
(reference: src/ray/object_manager/plasma/client.h — Create/Seal/Get/
Release/Delete/Contains + mutable-object acquire/release): each process
attaches the named arena (/dev/shm) and reads objects as zero-copy
memoryviews over the shared mmap. The build lives in src/shm_store.cc;
`make -C src` produces ray_tpu/_native/libshm_store.so.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import time
from typing import Optional, Tuple

from .handle_guard import HandleGuard

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libshm_store.so")

ID_LEN = 28  # cxx-const: kIdLen


class ShmStoreError(Exception):
    pass


class ObjectExistsError(ShmStoreError):
    pass


class StoreFullError(ShmStoreError):
    pass


def _load_lib():
    lib = ctypes.CDLL(_LIB_PATH)
    lib.rts_connect.restype = ctypes.c_void_p
    lib.rts_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_int]
    lib.rts_disconnect.argtypes = [ctypes.c_void_p]
    lib.rts_unlink.argtypes = [ctypes.c_char_p]
    for name in ("rts_create", "rts_ch_create", "rts_ch_write_acquire"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                       ctypes.POINTER(ctypes.c_uint64)]
    lib.rts_seal.restype = ctypes.c_int
    lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_get.restype = ctypes.c_int
    lib.rts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_uint64),
                            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    lib.rts_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_contains.restype = ctypes.c_int
    lib.rts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_delete.restype = ctypes.c_int
    lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_reclaim_dead_pins.restype = ctypes.c_int64
    lib.rts_reclaim_dead_pins.argtypes = [ctypes.c_void_p]
    lib.rts_pin_stats_json.restype = ctypes.c_int
    lib.rts_pin_stats_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int]
    for name in ("rts_used", "rts_capacity", "rts_num_objects"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_uint64
        fn.argtypes = [ctypes.c_void_p]
    lib.rts_ch_write_release.restype = ctypes.c_int
    lib.rts_ch_write_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_ch_wait.restype = ctypes.c_int64
    lib.rts_ch_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32, ctypes.c_int]
    lib.rts_ch_read.restype = ctypes.c_int64
    lib.rts_ch_read.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64)]
    return lib


_lib = None


def lib():
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


def available() -> bool:
    return os.path.exists(_LIB_PATH)


class ShmStore:
    def __init__(self, name: str, capacity: int = 256 * 1024 * 1024,
                 create: bool = True):
        self.name = name
        self._handle = lib().rts_connect(
            name.encode(), capacity, 1 if create else 0)
        if not self._handle and create:
            # A stale same-named arena from a dead session (or an
            # incompatible-layout build — magic mismatch) blocks
            # attachment forever; the creator owns the name, so
            # recreate it rather than wedge every worker spawn.
            # Recovery is serialized under an flock so two racing
            # creators can't unlink each other's freshly-recreated
            # arena (the loser re-attaches to the winner's instead).
            import fcntl

            with open(f"/dev/shm{name}.lock", "w") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                self._handle = lib().rts_connect(
                    name.encode(), capacity, 1)
                if not self._handle:
                    lib().rts_unlink(name.encode())
                    self._handle = lib().rts_connect(
                        name.encode(), capacity, 1)
        if not self._handle:
            raise ShmStoreError(f"Failed to attach shm store {name!r}")
        # Read side around every native call, write side around close():
        # a call racing teardown would deref a freed handle in C.
        self._guard = HandleGuard()
        # mmap the same arena for zero-copy buffer views.
        fd = os.open(f"/dev/shm{name}", os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._map = mmap.mmap(fd, size)
        finally:
            os.close(fd)

    def _h(self):
        """Live handle or raise — a closed store must never reach the C
        layer (null-handle deref segfaults; __del__-time teardown of
        channel users can outlive the runtime's store)."""
        h = self._handle
        if not h:
            raise ShmStoreError("store is closed")
        return h

    # -- immutable objects ------------------------------------------------
    def put(self, object_id: bytes, data: bytes | memoryview) -> None:
        self.put_frames(object_id, [data])

    def put_frames(self, object_id: bytes, parts) -> None:
        """Write a list of bytes-like parts as one object — each part
        memcpy'd straight into the arena (no host-side join)."""
        assert len(object_id) == ID_LEN
        parts = list(parts)  # sized twice below; generators must not drain
        total = sum(len(p) for p in parts)
        off = ctypes.c_uint64()
        with self._guard.read():
            rc = lib().rts_create(self._h(), object_id, total,
                                  ctypes.byref(off))
            if rc == -1:
                raise ObjectExistsError(object_id.hex())
            if rc == -2:
                raise StoreFullError(
                    f"{total} bytes do not fit "
                    f"(used {lib().rts_used(self._h())}"
                    f"/{lib().rts_capacity(self._h())})")
            if rc != 0:
                raise ShmStoreError(f"create failed rc={rc}")
            pos = off.value
            for p in parts:
                n = len(p)
                self._map[pos:pos + n] = p
                pos += n
            if lib().rts_seal(self._h(), object_id) != 0:
                raise ShmStoreError("seal failed")

    def get(self, object_id: bytes, *, pin: bool = False
            ) -> Optional[memoryview]:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        with self._guard.read():
            rc = lib().rts_get(self._h(), object_id, ctypes.byref(off),
                               ctypes.byref(size), 1 if pin else 0)
            if rc != 0:
                return None
            return memoryview(self._map)[off.value:off.value + size.value]

    def release(self, object_id: bytes) -> None:
        with self._guard.read():
            lib().rts_release(self._h(), object_id)

    def contains(self, object_id: bytes) -> bool:
        with self._guard.read():
            return bool(lib().rts_contains(self._h(), object_id))

    def reclaim_dead_pins(self) -> int:
        """Drop pins recorded by crashed processes; returns how many
        were reclaimed (reference: plasma client-disconnect cleanup).
        The allocator also does this lazily under memory pressure —
        call it eagerly when a worker death is observed."""
        with self._guard.read():
            return int(lib().rts_reclaim_dead_pins(self._h()))

    def pin_stats(self) -> dict:
        """Per-pid arena holdings from the slot table's pin records:
        {"pin_overflows": N, "pids": {"<pid>": {"pinned_bytes": ...,
        "pinned_objects": ..., "pins": ..., "creating_bytes": ...,
        "creating_objects": ...}}}. Each pinner is charged the full
        alloc_size of every object it pins (pins are shares of whole
        objects); SLOT_CREATED spans are charged to their writer."""
        import json

        with self._guard.read():
            buf = ctypes.create_string_buffer(1 << 20)
            rc = lib().rts_pin_stats_json(self._h(), buf, len(buf))
        if rc < 0:
            return {"pin_overflows": 0, "pids": {}}
        return json.loads(buf.value.decode())

    def delete(self, object_id: bytes) -> bool:
        with self._guard.read():
            if not self._handle:
                return False
            return lib().rts_delete(self._handle, object_id) == 0

    def used(self) -> int:
        with self._guard.read():
            return lib().rts_used(self._h())

    def capacity(self) -> int:
        with self._guard.read():
            return lib().rts_capacity(self._h())

    def num_objects(self) -> int:
        with self._guard.read():
            return lib().rts_num_objects(self._h())

    # -- mutable channel objects -----------------------------------------
    def channel_create(self, object_id: bytes, max_size: int) -> None:
        # ValueError, not assert: this guards a native OUT-OF-BOUNDS
        # read and must survive python -O.
        if len(object_id) != ID_LEN:
            raise ValueError(
                f"channel id must be {ID_LEN} bytes "
                f"(got {len(object_id)}: a short id makes the native "
                "side hash past the buffer)")
        off = ctypes.c_uint64()
        with self._guard.read():
            rc = lib().rts_ch_create(self._h(), object_id, max_size,
                                     ctypes.byref(off))
        if rc == -1:
            raise ObjectExistsError(object_id.hex())
        if rc != 0:
            raise ShmStoreError(f"channel create failed rc={rc}")

    def channel_write(self, object_id: bytes, data: bytes) -> None:
        if len(object_id) != ID_LEN:
            raise ValueError(f"channel id must be {ID_LEN} bytes")
        off = ctypes.c_uint64()
        with self._guard.read():
            rc = lib().rts_ch_write_acquire(
                self._h(), object_id, len(data), ctypes.byref(off))
            if rc != 0:
                raise ShmStoreError(f"write_acquire failed rc={rc}")
            self._map[off.value:off.value + len(data)] = data
            if lib().rts_ch_write_release(self._h(), object_id) != 0:
                raise ShmStoreError("write_release failed")

    def channel_read(self, object_id: bytes, *, min_version: int = -1,
                     timeout: float = 10.0) -> Tuple[bytes, int]:
        """Read the channel; blocks until version > min_version (a new
        write since the reader's last version).

        Waiting is futex-based (rts_ch_wait): the reader parks in the
        kernel on the channel's wake counter and the writer's
        futex_wake hands control straight over — polling here burned
        the single core the writer needed (167µs/call compiled-DAG
        floor came from exactly that). The ctypes call releases the
        GIL, so other Python threads keep running. The wake counter is
        sampled BEFORE each version check: a write landing between the
        check and the wait flips the counter and the wait returns
        immediately (no missed wakeup)."""
        deadline = time.monotonic() + timeout
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        with self._guard.read():
            seen = lib().rts_ch_wait(self._h(), object_id, 0xFFFFFFFF, 0)
        # Guard per iteration, not around the whole loop: each futex
        # wait is bounded to 0.5s, so a close() (write side) waits at
        # most one slice instead of the full read deadline.
        while True:
            with self._guard.read():
                v = lib().rts_ch_read(self._h(), object_id,
                                      ctypes.byref(off),
                                      ctypes.byref(size))
                if v >= 0 and v > min_version and size.value > 0:
                    data = bytes(
                        self._map[off.value:off.value + size.value])
                    # seqlock re-check: version must be unchanged after
                    # the copy
                    v2 = lib().rts_ch_read(self._h(), object_id,
                                           ctypes.byref(off),
                                           ctypes.byref(size))
                    if v2 == v:
                        return data, int(v)
                if v == -1:
                    raise ShmStoreError("channel missing")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("channel read timed out")
                if seen == -1:
                    # Initial sample raced channel creation (the channel
                    # exists now — rts_ch_read just found it): re-sample
                    # without blocking and re-check the version first.
                    seen = lib().rts_ch_wait(self._h(), object_id,
                                             0xFFFFFFFF, 0)
                    continue
                # Block until the next write (bounded so the deadline
                # holds); re-sample the counter for the next iteration.
                seen = lib().rts_ch_wait(
                    self._h(), object_id, seen,
                    max(1, int(min(remaining, 0.5) * 1000)))

    def close(self):
        # Write side: drains in-flight native calls and blocks new ones
        # before the handle is freed and the mapping unmapped.
        with self._guard.write():
            if self._handle:
                lib().rts_disconnect(self._handle)
                self._handle = None
                self._map.close()

    @staticmethod
    def unlink(name: str):
        lib().rts_unlink(name.encode())
