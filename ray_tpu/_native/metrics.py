"""ctypes binding for the native metrics registry (src/metrics.cc).

Capability-equivalent of the reference's native stats plumbing
(reference: src/ray/stats/metric.h — native metric objects whose values
are aggregated natively and exported as Prometheus text by the metrics
agent). ray_tpu.util.metrics routes its Counter/Gauge/Histogram storage
here when the library is built; pure-python fallback otherwise.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libmetrics.so")

KIND_COUNTER = 0  # cxx-const: KIND_COUNTER
KIND_GAUGE = 1  # cxx-const: KIND_GAUGE
KIND_HISTOGRAM = 2  # cxx-const: KIND_HISTOGRAM

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_LIB_PATH)
    lib.rtm_declare.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_char_p]
    lib.rtm_counter_add.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_double]
    lib.rtm_gauge_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                  ctypes.c_double]
    lib.rtm_series_remove.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.rtm_hist_observe.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_double,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int]
    lib.rtm_collect.restype = ctypes.c_long
    lib.rtm_collect.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.rtm_read.restype = ctypes.c_int
    lib.rtm_read.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_double)]
    lib.rtm_reset.argtypes = []
    _lib = lib
    return lib


def available() -> bool:
    if not os.path.exists(_LIB_PATH):
        return False
    try:
        _load()
        return True
    except (OSError, AttributeError):
        # AttributeError: stale .so missing a newer symbol.
        return False


def declare(name: str, kind: int, help_text: str = "") -> None:
    _load().rtm_declare(name.encode(), kind, help_text.encode())


def counter_add(name: str, labels: str, value: float) -> None:
    _load().rtm_counter_add(name.encode(), labels.encode(), value)


def gauge_set(name: str, labels: str, value: float) -> None:
    _load().rtm_gauge_set(name.encode(), labels.encode(), value)


def series_remove(name: str, labels: str) -> None:
    _load().rtm_series_remove(name.encode(), labels.encode())


def make_bounds(bounds: Sequence[float]):
    """Prebuilt ctypes bounds array for the observe hot path."""
    return (ctypes.c_double * len(bounds))(*bounds)


def hist_observe(name: str, labels: str, value: float,
                 bounds: Sequence[float]) -> None:
    hist_observe_raw(name, labels, value, make_bounds(bounds),
                     len(bounds))


def hist_observe_raw(name: str, labels: str, value: float,
                     c_bounds, n: int) -> None:
    _load().rtm_hist_observe(name.encode(), labels.encode(), value,
                             c_bounds, n)


def read(name: str, labels: str = "") -> Optional[float]:
    v = ctypes.c_double()
    if _load().rtm_read(name.encode(), labels.encode(),
                        ctypes.byref(v)):
        return v.value
    return None


def collect() -> str:
    lib = _load()
    # Size-then-fill races concurrent writers (the registry lock is
    # released between the two calls) — retry until the fill fits.
    needed = lib.rtm_collect(None, 0)
    while True:
        cap = needed + 256
        buf = ctypes.create_string_buffer(cap)
        needed = lib.rtm_collect(buf, cap)
        if needed < cap:
            return buf.value.decode()


def reset() -> None:
    _load().rtm_reset()
