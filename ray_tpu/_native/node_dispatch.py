"""ctypes binding for the native dispatch plane (libnode_dispatch.so).

The C loop (src/node_dispatch.cc) owns the node daemon's dispatch
socket: accept, framing, JSON admission headers, check-and-charge
against the resource ledger, spillback refusal and reply writing — all
off the GIL. Python drains a bounded ready queue (``next_event``) for
the work that needs policy: worker placement and task hand-off.

Every call here releases the GIL for its native duration (plain
``ctypes.CDLL``), which is the entire point: N drainer threads plus
the C loop thread overlap with task execution instead of serializing
on the interpreter lock.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Tuple

from .handle_guard import HandleGuard

_LIB_PATH = os.path.join(os.path.dirname(__file__),
                         "libnode_dispatch.so")

_lib = None

# Event flags (mirrors node_dispatch.cc; raylint --xp checks the pins).
FLAG_PRECHARGED = 1  # cxx-const: kFlagPrecharged
FLAG_JSON = 2  # cxx-const: kFlagJson

EV_MESSAGE = 0
EV_CLOSED = 1
EV_WORKER_DEAD = 2  # cxx-const: kEvWorkerDead


def available() -> bool:
    return os.path.exists(_LIB_PATH)


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_LIB_PATH)
    lib.nd_create.restype = ctypes.c_void_p
    lib.nd_create.argtypes = [ctypes.c_int, ctypes.c_int,
                              ctypes.c_ulonglong, ctypes.c_int]
    lib.nd_port.restype = ctypes.c_int
    lib.nd_port.argtypes = [ctypes.c_void_p]
    lib.nd_start.restype = ctypes.c_int
    lib.nd_start.argtypes = [ctypes.c_void_p]
    lib.nd_next.restype = ctypes.c_int
    lib.nd_next.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_ulonglong), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_uint), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_ulonglong)]
    lib.nd_free.restype = None
    lib.nd_free.argtypes = [ctypes.c_void_p]
    lib.nd_send.restype = ctypes.c_int
    lib.nd_send.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong,
                            ctypes.c_char_p, ctypes.c_ulonglong]
    for name in ("nd_set_node_id", "nd_set_load_tail"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nd_set_peers_json.restype = ctypes.c_int
    lib.nd_set_peers_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nd_set_ping_native.restype = None
    lib.nd_set_ping_native.argtypes = [ctypes.c_void_p, ctypes.c_int]
    for name in ("nd_ledger_set", "nd_ledger_try_charge",
                 "nd_ledger_charge", "nd_ledger_release"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nd_ledger_get.restype = ctypes.c_int
    lib.nd_ledger_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
    lib.nd_stats_json.restype = ctypes.c_int
    lib.nd_stats_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
    lib.nd_worker_register.restype = ctypes.c_int
    lib.nd_worker_register.argtypes = [
        ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p]
    lib.nd_worker_unregister.restype = ctypes.c_int
    lib.nd_worker_unregister.argtypes = [ctypes.c_void_p,
                                         ctypes.c_ulonglong]
    lib.nd_worker_acquire.restype = ctypes.c_longlong
    lib.nd_worker_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.nd_worker_release.restype = ctypes.c_int
    lib.nd_worker_release.argtypes = [ctypes.c_void_p,
                                      ctypes.c_ulonglong, ctypes.c_char_p]
    lib.nd_workers_json.restype = ctypes.c_int
    lib.nd_workers_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
    lib.nd_handoff_json.restype = ctypes.c_int
    lib.nd_handoff_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
    lib.nd_spilled.restype = ctypes.c_ulonglong
    lib.nd_spilled.argtypes = [ctypes.c_void_p]
    lib.nd_stop.restype = None
    lib.nd_stop.argtypes = [ctypes.c_void_p]
    lib.nd_destroy.restype = None
    lib.nd_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeDispatch:
    """One native dispatch server (the daemon's dispatch socket).

    Handle lifecycle follows the ``_native`` convention: every native
    call holds ``_guard.read()`` and ``destroy()`` nulls the handle
    under ``_guard.write()``, so a call racing teardown sees a clean
    "already destroyed" (no-op / timeout / StopIteration) instead of
    dereferencing freed memory in C.
    """

    def __init__(self, port: int = 0, bind_all: bool = False,
                 max_frame: int = 1 << 31, queue_cap: int = 1024):
        import json as _json

        self._json = _json
        self._lib = _load_lib()
        self._h = self._lib.nd_create(port, 1 if bind_all else 0,
                                      max_frame, queue_cap)
        if not self._h:
            raise OSError("nd_create failed (port in use?)")
        self._guard = HandleGuard()
        self.port = self._lib.nd_port(self._h)
        self._stopped = False

    def start(self) -> None:
        with self._guard.read():
            if self._h:
                self._lib.nd_start(self._h)

    # -- ready queue ----------------------------------------------------
    def next_event(self, timeout_ms: int = 200
                   ) -> Optional[Tuple[int, int, int, Optional[bytes]]]:
        """Block up to timeout_ms for one event.

        Returns (conn_id, kind, flags, body) — body is None for
        EV_CLOSED — or None on timeout. Raises StopIteration once the
        server has been stopped (drainers use it to exit)."""
        conn_id = ctypes.c_ulonglong()
        kind = ctypes.c_int()
        flags = ctypes.c_uint()
        data = ctypes.c_void_p()
        length = ctypes.c_ulonglong()
        with self._guard.read():
            if not self._h:
                raise StopIteration
            rc = self._lib.nd_next(self._h, timeout_ms,
                                   ctypes.byref(conn_id),
                                   ctypes.byref(kind),
                                   ctypes.byref(flags), ctypes.byref(data),
                                   ctypes.byref(length))
            if rc == 0:
                return None
            if rc < 0:
                raise StopIteration
            body = None
            if kind.value == EV_MESSAGE:
                try:
                    body = ctypes.string_at(data.value, length.value)
                finally:
                    self._lib.nd_free(data)
        return conn_id.value, kind.value, flags.value, body

    def send(self, conn_id: int, payload: bytes) -> bool:
        """Queue one reply frame; False if the server is stopped (a
        vanished conn is silently dropped, as with a closed socket)."""
        with self._guard.read():
            if not self._h:
                return False
            return self._lib.nd_send(self._h, conn_id, payload,
                                     len(payload)) == 0

    # -- Python-pushed reply context -------------------------------------
    def set_node_id(self, node_id: str) -> None:
        with self._guard.read():
            if self._h:
                self._lib.nd_set_node_id(self._h, node_id.encode())

    def set_load_report(self, report: Dict) -> None:
        """Push the heartbeat load report for natively-written pong and
        refusal replies. "available" is stripped — the C side splices
        in its own (always-fresh) ledger availability."""
        rest = {k: v for k, v in report.items() if k != "available"}
        tail = self._json.dumps(rest)[1:]  # drop the leading '{'
        with self._guard.read():
            if self._h:
                self._lib.nd_set_load_tail(self._h, tail.encode())

    def set_peers(self, peers: List[Dict]) -> None:
        """Push the spill-target digest: [{"id", "queued", "headroom",
        "avail": {...}}] — pre-filtered to alive, non-draining peers."""
        data = self._json.dumps(peers).encode()
        with self._guard.read():
            if self._h:
                self._lib.nd_set_peers_json(self._h, data)

    def set_ping_native(self, enabled: bool) -> None:
        with self._guard.read():
            if self._h:
                self._lib.nd_set_ping_native(self._h, 1 if enabled else 0)

    # -- resource ledger -------------------------------------------------
    def ledger_set(self, amounts: Dict[str, float]) -> None:
        data = self._json.dumps(amounts).encode()
        with self._guard.read():
            if self._h:
                self._lib.nd_ledger_set(self._h, data)

    def ledger_try_charge(self, amounts: Dict[str, float]) -> bool:
        data = self._json.dumps(amounts).encode()
        with self._guard.read():
            if not self._h:
                return False
            return self._lib.nd_ledger_try_charge(self._h, data) == 1

    def ledger_charge(self, amounts: Dict[str, float]) -> None:
        data = self._json.dumps(amounts).encode()
        with self._guard.read():
            if not self._h:
                return
            rc = self._lib.nd_ledger_charge(self._h, data)
        if rc == -1:
            # Same contract as ResourceSet.subtract.
            raise ValueError("resource would go negative")

    def ledger_release(self, amounts: Dict[str, float]) -> None:
        data = self._json.dumps(amounts).encode()
        with self._guard.read():
            if self._h:
                self._lib.nd_ledger_release(self._h, data)

    def ledger_available(self) -> Dict[str, float]:
        buf = ctypes.create_string_buffer(1 << 16)
        with self._guard.read():
            if not self._h:
                return {}
            rc = self._lib.nd_ledger_get(self._h, buf, len(buf))
        if rc < 0:
            return {}
        return self._json.loads(buf.value.decode())

    # -- idle-worker registry (native hand-off) --------------------------
    def worker_register(self, wid: int, fd: int, pid: int,
                        fids: List[bytes] = ()) -> bool:
        """Hand a worker socket to the native loop. The C side dups the
        fd (Python keeps its socket object for cold-path runs after
        ``worker_acquire``); ``fids`` lists the fn ids the worker has
        cached, matched against the driver's hybrid-frame header."""
        csv = ",".join(f.hex() for f in fids).encode()
        with self._guard.read():
            if not self._h:
                return False
            return self._lib.nd_worker_register(self._h, wid, fd, pid,
                                                csv) == 0

    def worker_unregister(self, wid: int) -> bool:
        """Deliberate removal (retire/discard): no death event fires."""
        with self._guard.read():
            if not self._h:
                return False
            return self._lib.nd_worker_unregister(self._h, wid) == 1

    def worker_acquire(self, timeout_ms: int = 200):
        """Check an idle worker out for the Python cold path. Returns
        its wid (>= 0 — ids start at 0, so the sentinels are negative),
        or None on timeout. Raises StopIteration once stopped."""
        with self._guard.read():
            if not self._h:
                raise StopIteration
            rc = self._lib.nd_worker_acquire(self._h, timeout_ms)
        if rc == -1:
            return None
        if rc < 0:
            raise StopIteration
        return int(rc)

    def worker_release(self, wid: int, fids: List[bytes] = ()) -> bool:
        """Return an acquired worker. False when the wid is unknown —
        the caller re-registers instead (fresh spawn, stale entry)."""
        csv = ",".join(f.hex() for f in fids).encode()
        with self._guard.read():
            if not self._h:
                return False
            return self._lib.nd_worker_release(self._h, wid, csv) == 1

    def workers(self) -> List[Dict]:
        """Registry snapshot: [{"wid","pid","state","age_s","tid"?}] —
        "tid" is the hex task id on busy entries (shm attribution
        labels); "age_s" is seconds since the last state transition
        (the outstanding-resource ledger's acquire-age)."""
        buf = ctypes.create_string_buffer(1 << 16)
        with self._guard.read():
            if not self._h:
                return []
            rc = self._lib.nd_workers_json(self._h, buf, len(buf))
        if rc < 0:
            return []
        return self._json.loads(buf.value.decode())

    def handoff(self) -> Dict[str, int]:
        """Hand-off plane counters: workers/idle/busy/py_owned/pending
        gauges (plus oldest_pending_s, the ledger's queue acquire-age)
        and handoffs/completed/worker_deaths/overflow totals."""
        buf = ctypes.create_string_buffer(1024)
        with self._guard.read():
            if not self._h:
                return {}
            rc = self._lib.nd_handoff_json(self._h, buf, len(buf))
        if rc < 0:
            return {}
        return self._json.loads(buf.value.decode())

    # -- stats -----------------------------------------------------------
    def spilled(self) -> int:
        with self._guard.read():
            if not self._h:
                return 0
            return int(self._lib.nd_spilled(self._h))

    def stats(self) -> Dict[str, Dict[str, float]]:
        buf = ctypes.create_string_buffer(1 << 18)
        with self._guard.read():
            if not self._h:
                return {}
            rc = self._lib.nd_stats_json(self._h, buf, len(buf))
        if rc < 0:
            return {}
        return self._json.loads(buf.value.decode())

    # -- lifecycle -------------------------------------------------------
    def stop(self) -> None:
        with self._guard.read():
            if self._stopped or not self._h:
                return
            self._stopped = True
            self._lib.nd_stop(self._h)

    def destroy(self) -> None:
        """Free the handle. Only after stop() AND after every drainer
        thread has exited next_event (in-flight readers are drained by
        the guard's write side; stop() above unblocks any thread parked
        in nd_next so the drain is bounded by one timeout)."""
        self.stop()
        with self._guard.write():
            if self._h:
                self._lib.nd_destroy(self._h)
                self._h = None
