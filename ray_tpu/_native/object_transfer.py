"""ctypes binding for the native object transfer plane
(src/object_transfer.cc).

Capability-equivalent of the reference's object manager client surface
(reference: src/ray/object_manager/ — PullManager/PushManager chunked
transfers between per-node plasma stores): each node serves its shm
arena on a TCP port; peers pull/push 28-byte-id objects in 4MiB chunks,
pinned on the sender and created+sealed on the receiver.
"""

from __future__ import annotations

import ctypes
import os
import time
from typing import Optional

from .handle_guard import HandleGuard
from .shm_store import ID_LEN

_LIB_PATH = os.path.join(os.path.dirname(__file__),
                         "libobject_transfer.so")

OP_PULL2 = 4  # cxx-const: OP_PULL2

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_LIB_PATH)
    lib.rto_serve.restype = ctypes.c_void_p
    lib.rto_serve.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                              ctypes.c_int, ctypes.c_int]
    lib.rto_port.restype = ctypes.c_int
    lib.rto_port.argtypes = [ctypes.c_void_p]
    lib.rto_stop.argtypes = [ctypes.c_void_p]
    lib.rto_connect.restype = ctypes.c_void_p
    lib.rto_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.rto_close.argtypes = [ctypes.c_void_p]
    for name in ("rto_pull", "rto_push"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                       ctypes.c_char_p]
    # Pull/push manager (policy layer — fair queueing, byte budget,
    # retry; reference pull_manager.h:52 / push_manager.h:30). Guarded:
    # a stale pre-manager .so must degrade to the plain client path,
    # not break the whole module.
    if hasattr(lib, "rtp_start"):
        lib.rto_stat.restype = ctypes.c_int64
        lib.rto_stat.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtp_start.restype = ctypes.c_void_p
        lib.rtp_start.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int]
        lib.rtp_submit.restype = ctypes.c_uint64
        lib.rtp_submit.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_int]
        lib.rtp_wait.restype = ctypes.c_int
        lib.rtp_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                 ctypes.c_int]
        lib.rtp_cancel.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtp_stats.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.rtp_stop.argtypes = [ctypes.c_void_p]
    # Multi-location pulls + relay (chunk-pipelined OP_PULL2).
    # Separately guarded: a pre-relay .so keeps the single-source
    # manager path working.
    if hasattr(lib, "rtp_submit_multi"):
        lib.rtp_submit_multi.restype = ctypes.c_uint64
        lib.rtp_submit_multi.argtypes = [ctypes.c_void_p,
                                         ctypes.c_uint64,
                                         ctypes.c_char_p,
                                         ctypes.c_char_p]
        lib.rtp_wait_src.restype = ctypes.c_int
        lib.rtp_wait_src.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                     ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.rtp_ep_stats.restype = ctypes.c_int
        lib.rtp_ep_stats.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.rto_pull2.restype = ctypes.c_int
        lib.rto_pull2.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_char_p, ctypes.c_char_p]
        lib.rto_serve_stats.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint64),
                                        ctypes.POINTER(ctypes.c_uint64)]
    # This library embeds its own store core — rts_connect et al for
    # attaching the LOCAL arena the transfer functions operate on.
    lib.rts_connect.restype = ctypes.c_void_p
    lib.rts_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_int]
    lib.rts_disconnect.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    if not os.path.exists(_LIB_PATH):
        return False
    try:
        _load()
        return True
    except (OSError, AttributeError):
        return False


class TransferError(Exception):
    pass


_ERRORS = {
    -1: "object not found",
    -2: "store full",
    -3: "wire error",
}


def _check_id(object_id: bytes) -> bytes:
    if len(object_id) != ID_LEN:
        raise ValueError(f"object id must be {ID_LEN} bytes")
    return object_id


def _record(event: str, **fields) -> None:
    """Flight-recorder breadcrumb (lazy import: _native must stay
    importable before the package finishes initialising)."""
    try:
        from ..observability import get_recorder
        get_recorder().record("object_transfer", event, **fields)
    except Exception:  # noqa: BLE001 - diagnostics must not break transfers
        pass


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransferError("connection closed mid-stream")
        buf += chunk
    return bytes(buf)


def fetch_object_bytes(host: str, port: int, object_id: bytes,
                       timeout: float = 30.0) -> Optional[bytes]:
    """Stream one object off a peer's transfer port into MEMORY — the
    chunk-framed OP_PULL2 wire protocol spoken directly, with no local
    arena residency. The driver uses this for objects larger than its
    own arena: the value reaches the caller while the location
    directory (and lineage) stay untouched. Returns None on a miss;
    raises TransferError on a wire error or sender-side abort."""
    import socket as _socket
    import struct as _struct

    _check_id(object_id)
    with _socket.create_connection((host, port),
                                   timeout=timeout) as sock:
        sock.settimeout(timeout)
        sock.sendall(bytes([OP_PULL2]) + object_id)
        (total,) = _struct.unpack("<q", _recv_exact(sock, 8))  # cxx-wire: rto-pull2-total
        if total < 0:
            return None
        out = bytearray()
        while len(out) < total:
            (ln,) = _struct.unpack("<I", _recv_exact(sock, 4))  # cxx-wire: rto-pull2-chunk
            if ln == 0xFFFFFFFF:  # kErrFrame: source failed mid-relay
                raise TransferError("sender aborted mid-stream")
            out += _recv_exact(sock, ln)
        _record("fetch_inline_done", object_id=object_id.hex()[:16],
                bytes=total)
        return bytes(out)


class TransferServer:
    """Serve this node's arena to peers (one per node). bind_all=True
    listens on 0.0.0.0 for real multi-host topologies; the default
    loopback bind keeps same-host tests unexposed."""

    def __init__(self, shm_name: str, port: int = 0,
                 bind_all: bool = False):
        lib = _load()
        self._lock = HandleGuard()
        self._h = lib.rto_serve(shm_name.encode(), 0, port,
                                1 if bind_all else 0)
        if not self._h:
            raise TransferError(
                f"failed to serve transfer plane for {shm_name}")
        self.port = lib.rto_port(self._h)

    def stats(self) -> dict:
        """Server-side counters: payload bytes served and how many
        pulls were answered from a mid-pull relay entry."""
        lib = _load()
        with self._lock.read():
            if not self._h or not hasattr(lib, "rto_serve_stats"):
                return {}
            bytes_out = ctypes.c_uint64()
            relay = ctypes.c_uint64()
            lib.rto_serve_stats(self._h, ctypes.byref(bytes_out),
                                ctypes.byref(relay))
        return {"bytes_out": bytes_out.value,
                "relay_served": relay.value}

    def stop(self) -> None:
        with self._lock.write():
            if self._h:
                _load().rto_stop(self._h)
                self._h = None


class TransferClient:
    """Persistent connection to one peer's transfer server, bound to
    the LOCAL arena objects land in / depart from."""

    def __init__(self, host: str, port: int, local_shm_name: str):
        lib = _load()
        self._lock = HandleGuard()
        self._conn = lib.rto_connect(host.encode(), port)
        if not self._conn:
            raise TransferError(f"cannot connect to {host}:{port}")
        self._store = lib.rts_connect(local_shm_name.encode(), 0, 0)
        if not self._store:
            lib.rto_close(self._conn)
            self._conn = None
            raise TransferError(f"cannot attach arena {local_shm_name}")

    def pull(self, object_id: bytes) -> bool:
        """Fetch the object from the peer into the local arena.
        True = transferred; False = already present locally."""
        with self._lock.read():
            if not self._conn:
                raise TransferError("client closed")
            rc = _load().rto_pull(self._conn, self._store,
                                  _check_id(object_id))
        if rc == 0:
            _record("pull_done", object_id=object_id.hex()[:16])
            return True
        if rc == -4:
            return False
        _record("pull_failed", object_id=object_id.hex()[:16],
                error=_ERRORS.get(rc, str(rc)))
        raise TransferError(
            f"pull failed: {_ERRORS.get(rc, rc)}")

    def push(self, object_id: bytes) -> None:
        """Send a local object to the peer (idempotent on the peer)."""
        with self._lock.read():
            if not self._conn:
                raise TransferError("client closed")
            rc = _load().rto_push(self._conn, self._store,
                                  _check_id(object_id))
        if rc != 0:
            _record("push_failed", object_id=object_id.hex()[:16],
                    error=_ERRORS.get(rc, str(rc)))
            raise TransferError(
                f"push failed: {_ERRORS.get(rc, rc)}")
        _record("push_done", object_id=object_id.hex()[:16])

    def close(self) -> None:
        lib = _load()
        # Write side: wait out in-flight pull/push before freeing the
        # native connection/arena handles they dereference.
        with self._lock.write():
            if self._conn:
                lib.rto_close(self._conn)
                self._conn = None
            if self._store:
                lib.rts_disconnect(self._store)
                self._store = None


_MGR_ERRORS = {
    -1: "object not found",
    -2: "store full",
    -3: "wire error (sender died or timed out, after retries)",
    -5: "wait timeout",
    -6: "manager stopped",
    -7: "unknown ticket",
}


class PullManager:
    """Native transfer-policy layer: N worker threads drain per-
    requester queues fairly (round-robin), admit transfers against a
    global in-flight byte budget tied to the local arena's capacity,
    retry wire errors with fresh connections, and surface sender-death
    aborts to the waiter. Concurrent pulls of one object coalesce.

    Reference: src/ray/object_manager/pull_manager.h:52 (fair queueing,
    in-flight budget, retry/cancel) and push_manager.h:30 (push
    scheduling under the same budget).
    """

    def __init__(self, local_shm_name: str, *, budget_bytes: int = 0,
                 workers: int = 4, timeout_ms: int = 30000,
                 retries: int = 2):
        lib = _load()
        if not hasattr(lib, "rtp_start"):
            raise TransferError(
                "libobject_transfer.so predates the pull manager — "
                "rebuild with `make -C src`")
        # rtp_stop deletes the native manager after draining only the
        # waiters already inside rtp_wait; a Python thread entering any
        # rtp_* concurrently with stop() would dereference freed memory
        # (ADVICE.md finding 1). Reader/writer guard: every native call
        # takes the read side, stop() takes the write side before
        # nulling the handle.
        self._lock = HandleGuard()
        self._h = lib.rtp_start(local_shm_name.encode(), budget_bytes,
                                workers, timeout_ms, retries)
        if not self._h:
            raise TransferError(
                f"cannot start pull manager on {local_shm_name}")

    def _handle(self) -> int:
        # Callers hold self._lock.read().
        if not self._h:
            raise TransferError(
                f"transfer failed: {_MGR_ERRORS[-6]}")
        return self._h

    def submit_pull(self, requester: int, host: str, port: int,
                    object_id: bytes) -> int:
        with self._lock.read():
            return _load().rtp_submit(self._handle(), requester,
                                      host.encode(), port,
                                      _check_id(object_id), 0)

    def submit_push(self, requester: int, host: str, port: int,
                    object_id: bytes) -> int:
        with self._lock.read():
            return _load().rtp_submit(self._handle(), requester,
                                      host.encode(), port,
                                      _check_id(object_id), 1)

    # One native wait slice. Short on purpose: a waiter must not pin
    # the read side of the teardown guard for an unbounded time, or
    # stop()'s write acquisition (which is what wakes native waiters
    # via rtp_stop) could never proceed.
    _WAIT_SLICE_MS = 50

    def _wait_loop(self, ticket: int, timeout_ms: int,
                   srcbuf=None) -> int:
        deadline = (None if timeout_ms < 0
                    else time.monotonic() + timeout_ms / 1000.0)
        while True:
            if deadline is None:
                chunk = self._WAIT_SLICE_MS
            else:
                remaining = int((deadline - time.monotonic()) * 1000)
                chunk = max(0, min(self._WAIT_SLICE_MS, remaining))
            with self._lock.read():
                if srcbuf is None:
                    rc = _load().rtp_wait(self._handle(), ticket,
                                          chunk)
                else:
                    rc = _load().rtp_wait_src(self._handle(), ticket,
                                              chunk, srcbuf,
                                              len(srcbuf))
                if rc == -5 and (deadline is not None
                                 and time.monotonic() >= deadline):
                    _load().rtp_cancel(self._h, ticket)
                    break
            if rc != -5:
                break  # completed (or failed) within this slice
        return rc

    def wait(self, ticket: int, timeout_ms: int = -1) -> None:
        """Block until the ticketed transfer completes; raises
        TransferError (with the failure cause) on anything but
        success. A timed-out wait CANCELS the ticket (the transfer
        itself keeps running for any coalesced waiters) so abandoned
        tickets cannot accumulate in a long-lived daemon."""
        rc = self._wait_loop(ticket, timeout_ms)
        if rc != 0:
            _record("managed_transfer_failed", ticket=int(ticket),
                    error=_MGR_ERRORS.get(rc, str(rc)))
            raise TransferError(
                f"transfer failed: {_MGR_ERRORS.get(rc, rc)}")

    def wait_src(self, ticket: int, timeout_ms: int = -1) -> str:
        """wait(), returning the winning source endpoint
        ("host:port", or "local" for an arena hit) on success."""
        srcbuf = ctypes.create_string_buffer(128)
        rc = self._wait_loop(ticket, timeout_ms, srcbuf)
        if rc != 0:
            _record("managed_transfer_failed", ticket=int(ticket),
                    error=_MGR_ERRORS.get(rc, str(rc)))
            raise TransferError(
                f"transfer failed: {_MGR_ERRORS.get(rc, rc)}")
        return srcbuf.value.decode("utf-8", "replace")

    def pull(self, requester: int, host: str, port: int,
             object_id: bytes, timeout_ms: int = -1) -> None:
        self.wait(self.submit_pull(requester, host, port, object_id),
                  timeout_ms)

    @property
    def supports_multi(self) -> bool:
        return hasattr(_load(), "rtp_submit_multi")

    def submit_pull_multi(self, requester: int, endpoints,
                          object_id: bytes) -> int:
        """Submit a pull with a fallback-ordered source list
        (`endpoints` = [(host, port), ...]). The manager dispatches to
        the least-loaded source and falls back through the rest on a
        miss or wire failure."""
        eps = ",".join(f"{h}:{p}" for h, p in endpoints)
        with self._lock.read():
            ticket = _load().rtp_submit_multi(
                self._handle(), requester, eps.encode(),
                _check_id(object_id))
        if ticket == 0:
            raise TransferError(f"bad endpoint list: {eps!r}")
        return ticket

    def pull_multi(self, requester: int, endpoints,
                   object_id: bytes, timeout_ms: int = -1) -> str:
        """Multi-source pull; returns the winning source endpoint."""
        src = self.wait_src(
            self.submit_pull_multi(requester, endpoints, object_id),
            timeout_ms)
        _record("pull_source", object_id=object_id.hex()[:16],
                source=src,
                candidates=[f"{h}:{p}" for h, p in endpoints])
        return src

    def ep_stats(self) -> dict:
        """Per-source accounting: {"total_bytes_in": N, "sources":
        {ep: {"inflight": .., "active": .., "bytes": ..}}}."""
        lib = _load()
        if not hasattr(lib, "rtp_ep_stats"):
            return {}
        cap = 4096
        with self._lock.read():
            h = self._handle()
            while True:
                buf = ctypes.create_string_buffer(cap)
                need = lib.rtp_ep_stats(h, buf, cap)
                if need < cap:
                    break
                cap = need + 1
        out = {"total_bytes_in": 0, "sources": {}}
        for line in buf.value.decode("utf-8", "replace").splitlines():
            parts = line.split()
            if len(parts) == 2 and parts[0] == "total":
                out["total_bytes_in"] = int(parts[1])
            elif len(parts) == 4:
                out["sources"][parts[0]] = {
                    "inflight": int(parts[1]),
                    "active": int(parts[2]),
                    "bytes": int(parts[3]),
                }
        return out

    def stats(self) -> dict:
        inflight = ctypes.c_uint64()
        queued = ctypes.c_uint64()
        active = ctypes.c_uint64()
        with self._lock.read():
            _load().rtp_stats(self._handle(), ctypes.byref(inflight),
                              ctypes.byref(queued),
                              ctypes.byref(active))
        return {"inflight_bytes": inflight.value,
                "queued": queued.value, "active": active.value}

    def stop(self) -> None:
        # Write side: drains in-flight rtp_* readers (rtp_wait itself
        # returns -6 once the native side starts stopping, so readers
        # cannot hold the guard forever) and blocks new ones while the
        # native manager is freed.
        with self._lock.write():
            if self._h:
                _load().rtp_stop(self._h)
                self._h = None
