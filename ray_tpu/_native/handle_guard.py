"""Reader/writer guard for native-handle lifecycles.

Every ctypes wrapper in ``_native`` hands a raw pointer (``self._h``)
into C calls. Teardown (``stop``/``close``) frees that pointer; a
concurrent call racing the teardown dereferences freed memory inside
the native library (ADVICE.md finding 1 — the ``rtp_stop`` /
``rtp_wait`` race). The fix is a tiny reader/writer lock:

- every native call takes the **read** side (many may run at once —
  the native layer is internally thread-safe while the handle lives);
- teardown takes the **write** side, so it waits for in-flight calls
  to drain and blocks new ones while it frees and nulls the handle.

Writers are preferred: once a teardown is waiting, new readers queue
behind it, so a steady stream of calls cannot starve shutdown.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class HandleGuard:
    """``with guard.read():`` around handle use, ``with
    guard.write():`` around teardown."""

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._readers = 0
        self._writing = False
        self._writers_waiting = 0

    @contextmanager
    def read(self):
        with self._cond:
            while self._writing or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writing or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writing = True
        try:
            yield
        finally:
            with self._cond:
                self._writing = False
                self._cond.notify_all()
