"""Shared pull plane for node daemons and the driver's RemotePlane.

Backed by the native PullManager (src/object_transfer.cc rtp_*): per-
requester fair queueing, a global in-flight byte budget tied to the
local arena's capacity, wire-error retry with reconnect, sender-death
abort surfaced to the caller, and same-object coalescing — the raylet
PullManager/PushManager policy, reference: src/ray/object_manager/
pull_manager.h:52, push_manager.h:30.

The old per-peer serial-lock client pool remains as the fallback when
the native library predates the manager (rebuild with `make -C src`).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Hashable, List, Tuple


def _site() -> str:
    try:
        from ray_tpu.observability.ledger import acquisition_site

        return acquisition_site()
    except Exception:  # noqa: BLE001
        return ""


class PullClientPool:
    def __init__(self, local_shm_name: str):
        self._shm_name = local_shm_name
        self._clients: Dict[Hashable, object] = {}
        self._locks: Dict[Hashable, threading.Lock] = {}
        self._lock = threading.Lock()
        # Single-flight per key: two tasks needing the same object must
        # not stream it twice (the loser of the native create races
        # would drain a full duplicate copy off the wire).
        self._inflight: Dict[Hashable, threading.Event] = {}
        # Outstanding-resource ledger view of the in-flight set:
        # key -> (t0, acquisition site, object id hex).
        self._inflight_meta: Dict[Hashable, Tuple[float, str, str]] = {}
        try:
            from ray_tpu.observability.ledger import register_collector

            register_collector("pull", self._ledger_entries, owner=self)
        except Exception:  # noqa: BLE001 — ledger is optional here
            pass
        self._mgr = None
        try:
            from .object_transfer import PullManager

            # budget 0 = half the arena (admission headroom for the
            # non-transfer users of the arena); 4 workers keep distinct
            # peers streaming concurrently under the shared budget.
            self._mgr = PullManager(local_shm_name)
        except Exception:  # noqa: BLE001 - stale .so without rtp_*
            self._mgr = None

    def pull(self, key: Hashable, endpoint: Tuple[str, int],
             object_id: bytes) -> None:
        """Pull object_id from the peer at `endpoint` into the local
        arena. Raises on failure. `key` doubles as the fairness bucket:
        requests from different keys round-robin, so one peer's (or
        consumer's) flood cannot starve the rest."""
        self.pull_multi(key, [endpoint], object_id)

    def pull_multi(self, key: Hashable,
                   endpoints: List[Tuple[str, int]],
                   object_id: bytes) -> str:
        """Pull from the first source that can serve the object,
        preferring the least-loaded (native path); `endpoints` is the
        fallback-ordered location list. Returns the winning source
        ("host:port", or "local" when the object was already here)."""
        if not endpoints:
            raise ValueError("pull_multi: empty endpoint list")
        while True:
            with self._lock:
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    self._inflight_meta[key] = (
                        time.time(), _site(), object_id.hex())
                    break
            # Another thread is fetching this key; once it lands, our
            # own attempt resolves instantly via the local-arena check.
            ev.wait()
        try:
            return self._pull_multi_locked(key, endpoints, object_id)
        finally:
            with self._lock:
                self._inflight.pop(key, None)
                self._inflight_meta.pop(key, None)
            ev.set()

    def _pull_multi_locked(self, key: Hashable,
                           endpoints: List[Tuple[str, int]],
                           object_id: bytes) -> str:
        if self._mgr is not None:
            requester = hash(key) & 0x7FFFFFFFFFFFFFFF
            if self._mgr.supports_multi:
                return self._mgr.pull_multi(requester, endpoints,
                                            object_id)
            last: Exception | None = None
            for host, port in endpoints:
                try:
                    self._mgr.pull(requester, host, port, object_id)
                    return f"{host}:{port}"
                except Exception as e:  # noqa: BLE001 - try next source
                    last = e
            raise last if last is not None else RuntimeError(
                "pull_multi: no endpoints")
        last = None
        for endpoint in endpoints:
            try:
                transferred = self._pull_fallback(key, endpoint,
                                                  object_id)
                return (f"{endpoint[0]}:{endpoint[1]}"
                        if transferred else "local")
            except Exception as e:  # noqa: BLE001 - try next source
                last = e
        raise last if last is not None else RuntimeError(
            "pull_multi: no endpoints")

    def _pull_fallback(self, key: Hashable, endpoint: Tuple[str, int],
                       object_id: bytes) -> bool:
        """Per-peer serial client (pre-manager behavior). Connecting
        happens under the PER-KEY lock only — one unreachable peer
        (kernel connect timeout) must not serialize pulls to healthy
        peers. Returns True when bytes moved (False = local hit)."""
        from .object_transfer import TransferClient

        with self._lock:
            lock = self._locks.get(key)
            if lock is None:
                lock = self._locks[key] = threading.Lock()
        try:
            with lock:
                with self._lock:
                    client = self._clients.get(key)
                if client is None:
                    client = TransferClient(endpoint[0], endpoint[1],
                                            self._shm_name)
                    with self._lock:
                        self._clients[key] = client
                return client.pull(object_id)
        except Exception:
            self.drop(key)
            raise

    def _ledger_entries(self) -> list:
        """Outstanding pulls for the resource ledger: one entry per
        in-flight (single-flight) fetch, aged from request time."""
        from ray_tpu.observability.ledger import entry

        with self._lock:
            meta = list(self._inflight_meta.items())
        return [entry("pull", "inflight", f"pull:{oid}", str(key),
                      t0, site=site)
                for key, (t0, site, oid) in meta]

    def stats(self) -> dict:
        if self._mgr is not None:
            out = self._mgr.stats()
            with contextlib.suppress(Exception):
                out.update(self._mgr.ep_stats())
            return out
        return {}

    def drop(self, key: Hashable) -> None:
        with self._lock:
            client = self._clients.pop(key, None)
            self._locks.pop(key, None)
        if client is not None:
            with contextlib.suppress(Exception):
                client.close()

    def close(self) -> None:
        if self._mgr is not None:
            with contextlib.suppress(Exception):
                self._mgr.stop()
            self._mgr = None
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            self._locks.clear()
        for c in clients:
            with contextlib.suppress(Exception):
                c.close()
