"""Shared pool of object-transfer pull clients.

One persistent TransferClient per peer endpoint, each serialized by its
own lock (the native connection handles one transfer at a time), with
drop-and-reconnect on error. Used by both the node daemon (pulling task
args into its arena) and the driver's RemotePlane (pulling results) —
the raylet PullManager role, reference: src/ray/object_manager/
pull_manager.h.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Hashable, Tuple


class PullClientPool:
    def __init__(self, local_shm_name: str):
        self._shm_name = local_shm_name
        self._clients: Dict[Hashable, object] = {}
        self._locks: Dict[Hashable, threading.Lock] = {}
        self._lock = threading.Lock()

    def pull(self, key: Hashable, endpoint: Tuple[str, int],
             object_id: bytes) -> None:
        """Pull object_id from the peer at `endpoint` into the local
        arena. Raises on failure (after dropping the cached client so
        a restarted peer gets a fresh connection). Connecting happens
        under the PER-KEY lock only — one unreachable peer (kernel
        connect timeout) must not serialize pulls to healthy peers."""
        from .object_transfer import TransferClient

        with self._lock:
            lock = self._locks.get(key)
            if lock is None:
                lock = self._locks[key] = threading.Lock()
        try:
            with lock:
                with self._lock:
                    client = self._clients.get(key)
                if client is None:
                    client = TransferClient(endpoint[0], endpoint[1],
                                            self._shm_name)
                    with self._lock:
                        self._clients[key] = client
                client.pull(object_id)
        except Exception:
            self.drop(key)
            raise

    def drop(self, key: Hashable) -> None:
        with self._lock:
            client = self._clients.pop(key, None)
            self._locks.pop(key, None)
        if client is not None:
            with contextlib.suppress(Exception):
                client.close()

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            self._locks.clear()
        for c in clients:
            with contextlib.suppress(Exception):
                c.close()
