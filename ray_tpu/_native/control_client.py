"""Client for the native control-plane daemon (src/control_plane.cc).

Capability-equivalent of the reference's GcsClient
(reference: src/ray/gcs/gcs_client/gcs_client.h:66 — InternalKVAccessor,
NodeInfoAccessor, ActorInfoAccessor, pubsub subscribe): a socket client
with a reader thread demuxing responses from pubsub pushes; subscribe
callbacks fire on a dedicated delivery thread.

launch_control_plane() spawns the daemon binary (the reference's
gcs_server process) and returns (Popen, port).
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import subprocess
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

_BIN = os.path.join(os.path.dirname(__file__), "control_plane")

# Ops — keep in sync with control_plane.cc.
OP_PING = 0  # cxx-const: OP_PING
OP_KV_PUT, OP_KV_GET, OP_KV_DEL, OP_KV_KEYS, OP_KV_EXISTS = 1, 2, 3, 4, 5
OP_SUBSCRIBE, OP_UNSUBSCRIBE, OP_PUBLISH = 10, 11, 12
OP_REGISTER_NODE, OP_HEARTBEAT, OP_LIST_NODES, OP_DRAIN_NODE = 20, 21, 22, 23
OP_REGISTER_ACTOR, OP_UPDATE_ACTOR, OP_GET_ACTOR = 30, 31, 32
OP_LIST_ACTORS, OP_GET_NAMED_ACTOR = 33, 34
OP_ADD_JOB, OP_LIST_JOBS = 40, 41
OP_STATS = 50  # cxx-const: OP_STATS
OP_SNAPSHOT = 60  # cxx-const: OP_SNAPSHOT

ST_OK, ST_NOT_FOUND, ST_EXISTS, ST_BAD_REQUEST = 0, 1, 2, 3

_ST_NAMES = {1: "NOT_FOUND", 2: "EXISTS", 3: "BAD_REQUEST"}


class ControlPlaneError(Exception):
    pass


class NotFoundError(ControlPlaneError):
    pass


class AlreadyExistsError(ControlPlaneError):
    pass


def available() -> bool:
    return os.path.exists(_BIN)


def launch_control_plane(*, port: int = 0, health_timeout_ms: int = 5000,
                         persist_path: Optional[str] = None,
                         bind_all: bool = False,
                         mirror_address: Optional[str] = None,
                         mirror_interval_ms: int = 200
                         ) -> Tuple[subprocess.Popen, int]:
    """Spawn the daemon; returns (process, bound port). persist_path
    enables crash-restart recovery from a local snapshot file;
    mirror_address="host:port" write-throughs state to an EXTERNAL
    store (another control-plane daemon in KV-only mode) so a fresh
    control plane on any host can take over — the capability of the
    reference's Redis-backed GCS (redis_store_client.h). bind_all
    listens on 0.0.0.0 so other hosts can join (multi-host clusters)."""
    cmd = [_BIN, "--port", str(port),
           "--health-timeout-ms", str(health_timeout_ms)]
    if persist_path:
        cmd += ["--persist", persist_path]
    if bind_all:
        cmd += ["--bind-all"]
    if mirror_address:
        cmd += ["--mirror", mirror_address,
                "--mirror-interval-ms", str(mirror_interval_ms)]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("PORT="):
        proc.kill()
        raise ControlPlaneError(f"daemon failed to start: {line!r}")
    return proc, int(line.strip().split("=", 1)[1])


# -- wire helpers -----------------------------------------------------------

def _pack_str(s) -> bytes:
    if isinstance(s, str):
        s = s.encode()
    return struct.pack("<I", len(s)) + s


class _Resp:
    __slots__ = ("event", "payload")

    def __init__(self):
        self.event = threading.Event()
        self.payload: Optional[bytes] = None


class _RespReader:
    """Cursor over a response payload (after req_id + status)."""

    def __init__(self, data: bytes, off: int = 0):
        self.d = data
        self.o = off

    def u8(self) -> int:
        v = self.d[self.o]
        self.o += 1
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.d, self.o)
        self.o += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from("<Q", self.d, self.o)
        self.o += 8
        return v

    def bytes_(self) -> bytes:
        n = self.u32()
        v = self.d[self.o:self.o + n]
        self.o += n
        return v

    def str_(self) -> str:
        return self.bytes_().decode()


class ControlClient:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 10.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._req_id = 0
        self._pending: Dict[int, _Resp] = {}
        self._plock = threading.Lock()
        self._subs: Dict[str, List[Callable[[bytes], None]]] = {}
        self._push_q: "queue.Queue" = queue.Queue()
        self._closed = threading.Event()
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True, name="ctrl-reader")
        self._reader.start()
        self._deliverer = threading.Thread(target=self._deliver_loop,
                                           daemon=True, name="ctrl-pubsub")
        self._deliverer.start()

    # -- transport ------------------------------------------------------
    def close(self) -> None:
        self._closed.set()
        self._push_q.put(None)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("control plane connection closed")
            buf.extend(chunk)
        return bytes(buf)

    def _read_loop(self) -> None:
        try:
            while not self._closed.is_set():
                (length,) = struct.unpack("<I", self._read_exact(4))  # cxx-wire: cp-frame-len
                body = self._read_exact(length)
                ftype = body[0]
                if ftype == 0:  # response
                    (req_id,) = struct.unpack_from("<Q", body, 1)  # cxx-wire: cp-req-id
                    with self._plock:
                        resp = self._pending.pop(req_id, None)
                    if resp is not None:
                        resp.payload = body[9:]  # status + result
                        resp.event.set()
                else:  # pubsub push
                    r = _RespReader(body, 1)
                    channel = r.str_()
                    payload = r.bytes_()
                    self._push_q.put((channel, payload))
        except (ConnectionError, OSError):
            pass
        finally:
            # Unblock every waiter — the connection is gone.
            with self._plock:
                pending = list(self._pending.values())
                self._pending.clear()
            for resp in pending:
                resp.event.set()

    def _deliver_loop(self) -> None:
        while True:
            item = self._push_q.get()
            if item is None:
                return
            channel, payload = item
            for cb in self._subs.get(channel, []):
                try:
                    cb(payload)
                except Exception:  # noqa: BLE001
                    pass

    def _request(self, op: int, body: bytes = b"",
                 timeout: float = 30.0) -> _RespReader:
        resp = _Resp()
        with self._wlock:
            self._req_id += 1
            req_id = self._req_id
            with self._plock:
                self._pending[req_id] = resp
            frame_body = b"\x00" + struct.pack("<Q", req_id) + \
                bytes([op]) + body
            self._sock.sendall(
                struct.pack("<I", len(frame_body)) + frame_body)
        if not resp.event.wait(timeout):
            with self._plock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"control plane op {op} timed out")
        if resp.payload is None:
            raise ConnectionError("control plane connection closed")
        r = _RespReader(resp.payload)
        status = r.u8()
        if status == ST_NOT_FOUND:
            raise NotFoundError(f"op {op}: not found")
        if status == ST_EXISTS:
            raise AlreadyExistsError(f"op {op}: already exists")
        if status != ST_OK:
            raise ControlPlaneError(
                f"op {op}: {_ST_NAMES.get(status, status)}")
        return r

    # -- KV (reference: InternalKVAccessor) -----------------------------
    def kv_put(self, key, value, overwrite: bool = True) -> None:
        self._request(OP_KV_PUT, _pack_str(key) + _pack_str(value)
                      + bytes([1 if overwrite else 0]))

    def kv_get(self, key) -> bytes:
        return self._request(OP_KV_GET, _pack_str(key)).bytes_()

    def kv_del(self, key) -> bool:
        try:
            self._request(OP_KV_DEL, _pack_str(key))
            return True
        except NotFoundError:
            return False

    def kv_exists(self, key) -> bool:
        return self._request(OP_KV_EXISTS, _pack_str(key)).u8() == 1

    def kv_keys(self, prefix="") -> List[str]:
        r = self._request(OP_KV_KEYS, _pack_str(prefix))
        return [r.str_() for _ in range(r.u32())]

    # -- pubsub (reference: InternalPubSub) -----------------------------
    def subscribe(self, channel: str,
                  callback: Callable[[bytes], None]) -> None:
        self._subs.setdefault(channel, []).append(callback)
        self._request(OP_SUBSCRIBE, _pack_str(channel))

    def unsubscribe(self, channel: str) -> None:
        self._subs.pop(channel, None)
        self._request(OP_UNSUBSCRIBE, _pack_str(channel))

    def publish(self, channel: str, payload) -> int:
        r = self._request(OP_PUBLISH,
                          _pack_str(channel) + _pack_str(payload))
        return r.u32()

    # -- nodes (reference: NodeInfoAccessor + health) -------------------
    def register_node(self, node_id: str, meta: str = "") -> None:
        self._request(OP_REGISTER_NODE,
                      _pack_str(node_id) + _pack_str(meta))

    def heartbeat(self, node_id: str, load: str = "") -> None:
        """Heartbeat, optionally piggybacking a load report (resource-view
        sync — the capability of reference ray_syncer.h:88; schedulers
        read the merged view back via list_nodes)."""
        body = _pack_str(node_id)
        if load:
            body += _pack_str(load)
        self._request(OP_HEARTBEAT, body)

    def drain_node(self, node_id: str) -> None:
        self._request(OP_DRAIN_NODE, _pack_str(node_id))

    def list_nodes(self) -> List[dict]:
        r = self._request(OP_LIST_NODES)
        out = []
        for _ in range(r.u32()):
            out.append({
                "node_id": r.str_(), "meta": r.str_(),
                "alive": bool(r.u8()), "draining": bool(r.u8()),
                "ms_since_heartbeat": r.u64(),
                "load": r.str_(),
            })
        return out

    # -- actors (reference: ActorInfoAccessor) --------------------------
    def register_actor(self, actor_id: str, name: str = "",
                       meta: str = "") -> None:
        self._request(OP_REGISTER_ACTOR, _pack_str(actor_id)
                      + _pack_str(name) + _pack_str(meta))

    def update_actor(self, actor_id: str, state: str) -> None:
        self._request(OP_UPDATE_ACTOR,
                      _pack_str(actor_id) + _pack_str(state))

    def get_actor(self, actor_id: str) -> dict:
        r = self._request(OP_GET_ACTOR, _pack_str(actor_id))
        return {"name": r.str_(), "state": r.str_(), "meta": r.str_()}

    def get_named_actor(self, name: str) -> str:
        return self._request(OP_GET_NAMED_ACTOR, _pack_str(name)).str_()

    def list_actors(self) -> List[dict]:
        r = self._request(OP_LIST_ACTORS)
        return [{"actor_id": r.str_(), "name": r.str_(),
                 "state": r.str_()} for _ in range(r.u32())]

    # -- jobs -----------------------------------------------------------
    def add_job(self, job_id: str, meta: str = "") -> None:
        self._request(OP_ADD_JOB, _pack_str(job_id) + _pack_str(meta))

    def list_jobs(self) -> List[dict]:
        r = self._request(OP_LIST_JOBS)
        return [{"job_id": r.str_(), "meta": r.str_()}
                for _ in range(r.u32())]

    # -- stats (reference: event_stats.cc per-handler stats) ------------
    def stats(self) -> Dict[int, dict]:
        r = self._request(OP_STATS)
        out = {}
        for _ in range(r.u32()):
            op = r.u8()
            count = r.u64()
            total = r.u64()
            out[op] = {"count": count, "total_us": total,
                       "mean_us": total / count if count else 0.0}
        return out

    def snapshot(self) -> None:
        """Force a durable snapshot now (normally timer-driven)."""
        self._request(OP_SNAPSHOT)

    def ping(self) -> int:
        return self._request(OP_PING).u64()
