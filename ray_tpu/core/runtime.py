"""The per-process core runtime.

Capability-equivalent to the reference's CoreWorker + raylet roles fused for
the local (single-host) runtime (reference: src/ray/core_worker/core_worker.h
— SubmitTask/CreateActor/SubmitActorTask/Put/Get/Wait; task retries and
lineage reconstruction from src/ray/core_worker/task_manager.h and
object_recovery_manager.h; actor transport semantics from
src/ray/core_worker/transport/direct_actor_task_submitter.h).

Tasks flow: submit → dependency resolution (on_ready callbacks) → scheduler
picks a node → executes on that node's pool → returns stored → refs resolve.
Actor calls bypass the scheduler and go straight to the actor's mailbox
(direct transport), in submission order per caller.
"""

from __future__ import annotations

import contextlib
import logging
import os
import queue
import threading
import time
import uuid
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .._private.config import config
from . import serialization
from .exceptions import (
    ActorDiedError,
    ObjectLostError,
    ObjectStoreFullError,
    TaskCancelledError,
    TaskError,
)
from .ids import ActorID, JobID, ObjectID, TaskID, put_counter
from .object_ref import ObjectRef
from .object_store import MemoryStore
from .reference_counter import ReferenceCounter
from .resources import CPU, TPU, ResourceSet
from .runtime_env import applied as _renv_applied
from .scheduler import NodeState, Scheduler
from .task import FunctionDescriptor, TaskSpec, TaskType
from ..observability import get_recorder, record_task_metrics
from ..util import tracing as _tracing

logger = logging.getLogger("ray_tpu")


# Split modules (VERDICT r3 #9); names re-exported here so every
# existing `from .runtime import X` keeps working.
from .actor_state import (  # noqa: F401,E402
    ActorState,
    ProcActorState,
    _ActorExit,
    _is_coro_fn,
    _wrap,
)
from .runtime_support import (  # noqa: F401,E402
    FunctionManager,
    ObjectRefGenerator,
    RuntimeContext,
    TaskEventBuffer,
    _GeneratorState,
    _ctx,
)

class _ShmMarker:
    """Memory-store placeholder for a payload living in the shm plane.

    node_id records which node's arena holds the payload (None = this
    process's own arena) — the ownership-based object directory of the
    multi-host plane (reference: ownership_based_object_directory.h:
    the owner knows each object's locations). `locations` extends the
    directory to MULTI-location: every node that confirmed a completed
    pull (via the daemon's pull_complete report) is an additional
    source, so later consumers spread their pulls instead of starring
    the primary. `pending` is the dispatch-ordered list of nodes a
    fetch hint was handed to — the relay tree is built over it (a new
    consumer's preferred source is pending[(i-1)//2], its binary-tree
    parent), so an N-node broadcast forms pipelined chains instead of
    N direct pulls from the producer.

    Mutated from dispatcher + connection-reader threads; the per-field
    operations below are single bytecode-level set/list mutations
    (atomic under the GIL), and every reader treats the contents as
    fallback-ordered hints — a stale entry costs one extra candidate
    attempt, never correctness."""

    __slots__ = ("key", "contained_refs", "node_id", "locations",
                 "pending")

    def __init__(self, key: bytes, node_id: Optional[str] = None):
        self.key = key
        self.node_id = node_id
        self.contained_refs = ()
        self.locations: set = set()
        self.pending: list = []

    def add_location(self, node_id: str) -> None:
        self.locations.add(node_id)

    def discard_location(self, node_id: str) -> None:
        self.locations.discard(node_id)
        with contextlib.suppress(ValueError):
            self.pending.remove(node_id)

    def total_bytes(self) -> int:
        return len(self.key)  # marker itself is tiny; payload is in shm


# ---------------------------------------------------------------------------
# Runtime
# ---------------------------------------------------------------------------

class ProcNodeState(NodeState):
    """A schedulable node whose tasks execute in spawned worker
    PROCESSES (worker_proc.py) instead of in-process threads. The
    thread-pool executor threads only drive the socket round-trips; the
    user code runs out-of-process (true parallelism, crash isolation).
    Actors are hosted by dedicated workers leased from the same pool."""

    def __init__(self, node_id: str, total, pool):
        super().__init__(node_id, total, max_workers=pool.num_workers + 4)
        self.pool = pool

    def shutdown(self):
        super().shutdown()
        self.pool.shutdown()


class Runtime:
    def __init__(self, *, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 num_worker_procs: int = 0,
                 cluster_address: Optional[str] = None,
                 advertise_host: str = "127.0.0.1",
                 _system_config: Optional[Dict[str, Any]] = None):
        config.apply(_system_config)
        self.job_id = JobID.from_random()
        self.remote_plane = None  # set below in cluster mode
        # Session directory first: the spiller lands under it.
        from .._private import session as _session

        self.session_dir = _session.new_session()
        # Continuous observability: the driver carries its own always-on
        # profiler ring + metrics-history scraper; local pool workers
        # share the ring via RAY_TPU_CONTPROF_DIR below.
        self.contprof_dir = (config.contprof_dir
                             or os.path.join(self.session_dir, "contprof"))
        self._contprof = None
        self._tsdb = None
        self._ledger = None
        try:
            from ..observability import continuous as _contmod
            from ..observability import tsdb as _tsdbmod

            if config.contprof_enabled:
                self._contprof = _contmod.start_continuous_profiler(
                    "driver", directory=self.contprof_dir)
            if config.metrics_history_enabled:
                self._tsdb = _tsdbmod.start_scraper()
            if config.ledger_enabled:
                from ..observability import ledger as _ledgermod

                self._ledger = _ledgermod.start_ledger()
        except Exception:  # noqa: BLE001 — observability must not stop init
            pass
        spiller = None
        if config.memory_store_spill_threshold_bytes > 0:
            from .spilling import ObjectSpiller

            spiller = ObjectSpiller(
                config.object_spilling_dir
                or os.path.join(self.session_dir, "spill"))
        self.spiller = spiller
        self.store = MemoryStore(
            spiller=spiller,
            high_watermark_bytes=config.memory_store_spill_threshold_bytes)
        self.reference_counter = ReferenceCounter(self._on_refcount_zero)
        self.function_manager = FunctionManager()
        self.events = TaskEventBuffer()
        self.scheduler = Scheduler(self._dispatch)
        self.lineage: Dict[ObjectID, TaskSpec] = {}
        self.lineage_lock = threading.Lock()
        self._pending_tasks: Dict[TaskID, TaskSpec] = {}
        self._pending_lock = threading.Lock()
        self._cancelled: set = set()
        self._generators: Dict[TaskID, _GeneratorState] = {}
        self._actors: Dict[ActorID, ActorState] = {}
        # Named actors keyed "namespace/name" (reference: namespaces —
        # names are scoped, usage-guide namespace semantics).
        self._named_actors: Dict[str, ActorID] = {}
        self._scoped_by_actor: Dict[ActorID, str] = {}
        self.namespace: str = "default"  # init(namespace=...) overrides
        self._actors_lock = threading.Lock()
        self._ref_registry: Dict[ObjectID, int] = {}
        self._shutdown = False
        # Zero-refcount cleanup runs on a dedicated thread: finalizers fire
        # on whatever thread drops the last reference (possibly while locks
        # are held), and releasing a lineage entry cascades further ref
        # drops — doing the work here keeps it deadlock- and recursion-free.
        self._gc_queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._gc_thread = threading.Thread(
            target=self._gc_loop, name="ref-gc", daemon=True)
        self._gc_thread.start()
        # Borrows held by serialized copies inside stored objects:
        # containing ObjectID → IDs of refs pickled inside it. Released
        # when the containing object is deleted (reference borrowing:
        # reference_count.h WrapObjectIds/nested-ref semantics).
        self._contained: Dict[ObjectID, List[ObjectID]] = {}
        self._contained_lock = threading.Lock()
        # Native shared-memory plane for large objects (plasma-equivalent;
        # src/shm_store.cc). Inline objects stay in the memory store
        # (reference inlines <100KB, core_worker.h memory store).
        self.shm = None
        try:
            from .._native.shm_store import ShmStore, available

            if available():
                self._shm_name = f"/ray_tpu_{self.job_id.hex()}"
                self.shm = ShmStore(
                    self._shm_name,
                    capacity=config.object_store_memory_bytes)
        except Exception:  # noqa: BLE001 — shm plane is optional
            self.shm = None

        if cluster_address is not None:
            # Joining a daemon-backed cluster: the driver contributes no
            # schedulable resources by default — work goes to the node
            # daemons (reference: a driver's raylet still schedules, but
            # the TPU deployment model is drivers on CPU frontends).
            if num_cpus is None:
                num_cpus = 0.0
            if num_tpus is None:
                num_tpus = 0.0
        if num_cpus is None:
            num_cpus = float(os.cpu_count() or 1)
        if num_tpus is None:
            num_tpus = float(self._detect_tpus())
        total = {CPU: num_cpus}
        if num_tpus:
            total[TPU] = num_tpus
            # Advertise accelerator identity: "TPU-<type>" (+ pod
            # membership) so accelerator_type= and SliceAffinity route
            # here (reference: tpu.py accelerator resources).
            from .._private import accelerators

            total.update(accelerators.pod_resources())
        total.update(resources or {})
        self.head_node_id = "node-head"
        head = NodeState(
            self.head_node_id, ResourceSet(total),
            max_workers=max(4, int(num_cpus) * 2),
        )
        if cluster_address is not None and not any(total.values()):
            # Zero-resource driver joining a daemon cluster: keep the
            # head node OUT of placement, or zero-resource tasks and
            # actors (the actor default) would all run local-first in
            # the driver instead of on the daemons.
            head.schedulable = False
        self.scheduler.add_node(head)

        # Out-of-process execution plane: spawned worker processes behind
        # a pool node (see worker_proc.py). Objects ride the shared shm
        # store; only ids cross the sockets.
        self.worker_pool = None
        self.log_monitor = None
        if num_worker_procs > 0:
            from .worker_proc import WorkerPool

            self.worker_pool = WorkerPool(
                num_worker_procs,
                shm_name=(self._shm_name if self.shm is not None else None),
                logs_dir=os.path.join(self.session_dir, "logs"),
                # Local pool workers never own the (single) chip the
                # driver drives; loading the TPU plugin at startup
                # risks concurrent-registration segfaults (see
                # node/daemon.py worker_env).
                env={"PALLAS_AXON_POOL_IPS": "",
                     "RAY_TPU_CONTPROF_DIR": self.contprof_dir})
            self.scheduler.add_node(ProcNodeState(
                "node-procs", ResourceSet({CPU: float(num_worker_procs)}),
                self.worker_pool))
            if config.log_to_driver:
                from .._private.log_monitor import LogMonitor

                self.log_monitor = LogMonitor(
                    os.path.join(self.session_dir, "logs")).start()

        # Memory monitor + OOM worker-killing (reference:
        # memory_monitor.h, worker_killing_policy.h): above the usage
        # threshold, kill the last-submitted retriable task's worker —
        # it retries instead of the kernel OOM-killer downing the node.
        self.memory_monitor = None
        self._running_proc: Dict[TaskID, tuple] = {}
        self._running_seq = 0
        self._running_lock = threading.Lock()
        if (self.worker_pool is not None
                and config.memory_monitor_threshold > 0):
            from .memory_monitor import MemoryMonitor, usage_fn_from_config

            self.memory_monitor = MemoryMonitor(
                self._memory_victims,
                threshold=config.memory_monitor_threshold,
                interval_s=config.memory_monitor_interval_ms / 1000.0,
                usage_fn=usage_fn_from_config(),
            ).start()

        # Multi-host plane: join a control-plane-backed cluster of node
        # daemons (ray-tpu start); their nodes appear in the scheduler
        # as RemoteNodeState entries (core/remote_node.py).
        if cluster_address is not None:
            from .remote_node import RemotePlane

            self.remote_plane = RemotePlane(
                self, cluster_address, advertise_host=advertise_host)

    @staticmethod
    def _detect_tpus() -> int:
        if config.tpu_devices_per_host:
            return config.tpu_devices_per_host
        # Env-based discovery first (TPU_CHIPS_PER_HOST_BOUNDS /
        # TPU_VISIBLE_CHIPS — reference: _private/accelerators/tpu.py);
        # falls back to probing jax.
        from .._private import accelerators

        return accelerators.num_chips_per_host()

    # ------------------------------------------------------------------
    # Ref bookkeeping
    # ------------------------------------------------------------------
    def register_ref(self, ref: ObjectRef) -> ObjectRef:
        self.reference_counter.add_local_ref(ref.id())
        weakref.finalize(ref, self._finalize_ref, ref.id())
        return ref

    def _finalize_ref(self, oid: ObjectID):
        if not self._shutdown:
            self.reference_counter.remove_local_ref(oid)

    def _on_refcount_zero(self, oid: ObjectID):
        self._gc_queue.put(oid)

    def _gc_loop(self):
        while True:
            oid = self._gc_queue.get()
            if oid is None:
                return
            self.store.delete([oid])
            if self.shm is not None:
                try:
                    self.shm.delete(oid.binary())
                except Exception:  # noqa: BLE001
                    pass
            with self._contained_lock:
                contained = self._contained.pop(oid, [])
            for cid in contained:
                self.reference_counter.remove_borrow(cid)
            with self.lineage_lock:
                spec = self.lineage.pop(oid, None)
            del spec  # cascading finalizers fire here, outside any lock

    def _store(self, oid: ObjectID, data, is_error: bool = False):
        """All object writes funnel here so contained-ref borrows are
        tracked against the containing object's lifetime. Large payloads
        go to the shared-memory plane; the memory store keeps a marker."""
        if data.contained_refs:
            with self._contained_lock:
                self._contained[oid] = [r.id() for r in data.contained_refs]
        if (self.shm is not None and not is_error
                and data.total_bytes() > config.inline_object_max_bytes):
            try:
                # frames() parts are memcpy'd straight into the arena —
                # the single copy this path needs.
                self.shm.put_frames(oid.binary(), data.frames())
                self.store.put(oid, _ShmMarker(oid.binary()),
                               is_error=False)
                return
            except Exception:  # noqa: BLE001 — full/duplicate: keep inline
                pass
        # The memory store RETAINS the object: materialize any borrowed
        # buffer views first or a later caller-side mutation (e.g. the
        # task reusing its result array) would corrupt the store.
        self.store.put(oid, data.ensure_owned(), is_error=is_error)

    def _load_data(self, stored) -> "serialization.SerializedObject":
        """Resolve a stored entry, pulling shm-resident payloads back as
        zero-copy views. Raises KeyError if the shm copy was evicted."""
        d = stored.data
        if not isinstance(d, _ShmMarker):
            return d
        # Remote-located payload (multi-host plane): pull it into the
        # local arena first (reference: raylet PullManager restoring a
        # needed object from its remote location). Any marker with a
        # primary OR confirmed secondary locations is fetchable.
        if ((d.node_id is not None or getattr(d, "locations", None))
                and self.remote_plane is not None
                and (self.shm is None or not self.shm.contains(d.key))):
            try:
                self.remote_plane.ensure_local(d)
            except ObjectStoreFullError:
                # The object is alive on remote nodes but won't fit in
                # OUR arena. Stream it straight into memory instead:
                # the marker and its location directory stay intact, so
                # no destructive delete + lineage re-execution.
                blob = self.remote_plane.fetch_inline(d)
                if blob is None:
                    raise KeyError(d.key) from None
                return serialization.SerializedObject.from_bytes(blob)
        # Pin while copying out: an unpinned region can be evicted and
        # its bytes reused by a concurrent put mid-read.
        view = self.shm.get(d.key, pin=True) if self.shm is not None else None
        if view is None:
            raise KeyError(d.key)
        try:
            return serialization.SerializedObject.from_bytes(view)
        finally:
            self.shm.release(d.key)

    def serialization_noted_ref(self, ref: ObjectRef):
        serialization.get_context()._note_ref(ref)

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        task_id = _ctx.task_id or TaskID.for_task(self.job_id)
        oid = ObjectID.for_put(task_id, put_counter.next())
        data = serialization.serialize(value)
        self._store(oid, data)
        return self.register_ref(ObjectRef(oid))

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        ids = [r.id() for r in refs]
        self._maybe_reconstruct(ids)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else max(0.001, deadline - time.monotonic()))
            stored = self.store.get(ids, remaining)
            evicted: List[ObjectID] = []
            loaded = []
            for oid, s in zip(ids, stored):
                try:
                    loaded.append((s, self._load_data(s)))
                except KeyError:
                    evicted.append(oid)  # shm copy evicted under pressure
            if not evicted:
                out = []
                for s, data in loaded:
                    value = serialization.deserialize(data)
                    if s.is_error:
                        raise value
                    out.append(value)
                return out
            # Reconstruct evicted objects through their lineage
            # (reference: object_recovery_manager.h — spilled/lost copies
            # rebuilt by resubmitting the creating task). Objects with no
            # lineage (ray.put data) can never come back — fail fast
            # instead of blocking forever.
            with self.lineage_lock:
                unrecoverable = [o for o in evicted
                                 if o not in self.lineage]
            if unrecoverable:
                raise ObjectLostError(
                    "object(s) evicted from the shared-memory store and "
                    "not reconstructable (no lineage): "
                    + ", ".join(o.hex()[:16] for o in unrecoverable))
            self.store.delete(evicted)
            self._maybe_reconstruct(evicted)

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float],
             fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        id_to_ref = {r.id(): r for r in refs}
        ready, not_ready = self.store.wait(
            [r.id() for r in refs], num_returns, timeout)
        if fetch_local:
            # Reference semantics (ray.wait fetch_local=True): a ready
            # ref's VALUE must be resident in the local store, not just
            # located somewhere in the cluster. Pull remote-only
            # payloads down before reporting them ready; a pull that
            # fails leaves the ref ready — get() owns the
            # reconstruction/inline-stream fallback path.
            for oid in ready:
                stored = self.store.get_if_exists(oid)
                d = stored.data if stored is not None else None
                if (isinstance(d, _ShmMarker)
                        and self.remote_plane is not None
                        and (d.node_id is not None
                             or getattr(d, "locations", None))
                        and (self.shm is None
                             or not self.shm.contains(d.key))):
                    try:
                        self.remote_plane.ensure_local(d)
                    except (KeyError, ObjectStoreFullError):
                        pass
        return ([id_to_ref[i] for i in ready], [id_to_ref[i] for i in not_ready])

    def as_future(self, ref: ObjectRef):
        from concurrent.futures import Future
        fut: Future = Future()

        def _cb(oid):
            try:
                fut.set_result(self.get([ref])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        self.store.on_ready(ref.id(), _cb)
        return fut

    # ------------------------------------------------------------------
    # Task submission
    # ------------------------------------------------------------------
    def submit_task(self, func: Callable, descriptor: FunctionDescriptor,
                    args, kwargs, opts: Dict[str, Any]) -> Any:
        from .task import build_resources
        task_id = TaskID.for_task(self.job_id)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns in ("streaming", "dynamic")
        n_rets = 0 if streaming else num_returns
        spec = TaskSpec(
            task_id=task_id,
            task_type=TaskType.NORMAL_TASK,
            descriptor=descriptor,
            args=tuple(args),
            kwargs=dict(kwargs),
            num_returns=num_returns,
            resources=build_resources(opts, is_actor=False),
            return_ids=[ObjectID.for_return(task_id, i) for i in range(n_rets)],
            max_retries=opts.get("max_retries", config.default_max_retries),
            retry_exceptions=opts.get("retry_exceptions", False),
            scheduling_strategy=opts.get("scheduling_strategy"),
            label_selector=opts.get("label_selector"),
            name=opts.get("name", ""),
            runtime_env=opts.get("runtime_env"),
            max_calls=opts.get("max_calls", 0),
        )
        spec.retries_left = spec.max_retries
        gen_state = None
        # Submission span: roots a trace (or joins the caller's), and
        # its id becomes the parent of the downstream execution spans —
        # the Dapper propagation chain starts here.
        with _tracing.span(f"submit:{spec.display_name()}",
                           "task_submit", task_id=task_id.hex()) as sid:
            spec.trace_id = _tracing.current_trace_id()
            spec.parent_span_id = sid
            spec.timing["submitted"] = time.time()
            if streaming:
                gen_state = _GeneratorState()
                self._generators[task_id] = gen_state
            self._record_lineage(spec)
            with self._pending_lock:
                self._pending_tasks[task_id] = spec
            self._submit_when_ready(spec)
        if streaming:
            return ObjectRefGenerator(task_id, gen_state)
        refs = [self.register_ref(ObjectRef(oid)) for oid in spec.return_ids]
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def _record_lineage(self, spec: TaskSpec):
        with self.lineage_lock:
            for oid in spec.return_ids:
                self.lineage[oid] = spec

    def _submit_when_ready(self, spec: TaskSpec):
        """Dependency resolution: top-level ObjectRef args must exist."""
        deps = [a.id() for a in spec.args if isinstance(a, ObjectRef)]
        deps += [v.id() for v in spec.kwargs.values() if isinstance(v, ObjectRef)]
        deps = list(dict.fromkeys(deps))
        if not deps:
            self.scheduler.submit(spec)
            return
        remaining = {"n": len(deps)}
        lock = threading.Lock()

        def on_ready(_oid):
            with lock:
                remaining["n"] -= 1
                if remaining["n"] != 0:
                    return
            self.scheduler.submit(spec)

        for d in deps:
            self.store.on_ready(d, on_ready)
        # Reconstruction safety net: deps might have been lost.
        self._maybe_reconstruct(deps)

    # ------------------------------------------------------------------
    # Actor API
    # ------------------------------------------------------------------
    def create_actor(self, cls: type, args, kwargs,
                     opts: Dict[str, Any]) -> "ActorID":
        from .task import build_resources
        name = opts.get("name") or ""
        actor_id = ActorID.of(self.job_id)
        if name:
            ns = opts.get("namespace") or self.namespace
            scoped = f"{ns}/{name}"
            # Reserve the name BEFORE starting any threads / holding any
            # resources, so a duplicate-name failure leaks nothing.
            with self._actors_lock:
                existing = self._named_actors.get(scoped)
                if existing is not None:
                    if opts.get("get_if_exists"):
                        return existing
                    raise ValueError(
                        f"Actor name {name!r} already taken in "
                        f"namespace {ns!r}")
                self._named_actors[scoped] = actor_id
                self._scoped_by_actor[actor_id] = scoped
        resources = build_resources(opts, is_actor=True)
        # Acquire placement synchronously through the scheduler by running
        # the creation as a task that starts the actor threads on a node.
        done = threading.Event()
        box: Dict[str, Any] = {}

        creation_id = TaskID.for_actor_task(actor_id)
        spec = TaskSpec(
            task_id=creation_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            descriptor=FunctionDescriptor(cls.__module__, cls.__qualname__),
            args=tuple(args), kwargs=dict(kwargs),
            num_returns=0, resources=resources,
            scheduling_strategy=opts.get("scheduling_strategy"),
            label_selector=opts.get("label_selector"),
            name=name, actor_id=actor_id, actor_class=cls,
            actor_creation_opts=opts,
        )
        # Creation joins the caller's trace like task/method submission
        # does — without it, trace-scoped task-graph reconstruction
        # (state.list_tasks deps/returns) drops actor-creation nodes.
        spec.trace_id = _tracing.current_trace_id()
        spec.timing["submitted"] = time.time()

        def on_placed(node: NodeState):
            t0 = time.monotonic()
            try:
                if node.is_remote:
                    from .remote_node import remote_actor_state_cls

                    state_cls = remote_actor_state_cls()
                else:
                    state_cls = (ProcActorState if isinstance(
                        node, ProcNodeState) else ActorState)
                st = state_cls(
                    self, actor_id, cls, spec.args, spec.kwargs,
                    node=node, name=name or actor_id.hex()[:8],
                    max_concurrency=opts.get("max_concurrency", 1),
                    max_restarts=opts.get(
                        "max_restarts", config.default_actor_max_restarts),
                    max_task_retries=opts.get("max_task_retries", 0),
                    concurrency_groups=opts.get("concurrency_groups"),
                    resources=resources,
                    runtime_env=opts.get("runtime_env"),
                    detached=opts.get("lifetime") == "detached",
                )
                with self._actors_lock:
                    self._actors[actor_id] = st
                # Named/detached actors on the daemon plane are
                # registered in the control plane's actor table so ANY
                # driver can find them (reference: GcsActorManager +
                # named-actor lookup across jobs). A name held by
                # ANOTHER driver's live actor is a duplicate — the
                # same cross-job error the reference raises.
                if (self.remote_plane is not None and node.is_remote
                        and (name or st.detached)):
                    from .._native.control_client import (
                        AlreadyExistsError,
                    )

                    ns = opts.get("namespace") or self.namespace
                    scoped = f"{ns}/{name}" if name else ""
                    try:
                        self.register_in_actor_table(st, scoped)
                        if st.detached and st.max_restarts > 0:
                            # Cluster-owned reconstruction: survivors
                            # recreate it from this spec after a node
                            # death, no driver required.
                            self.remote_plane.persist_detached_spec(st)
                    except AlreadyExistsError:
                        st.kill()
                        raise ValueError(
                            f"Actor name {name!r} already taken in "
                            f"namespace {ns!r} (held by another "
                            f"driver)") from None
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                box["ok"] = True
                # Creation-task event (reference: creation tasks appear
                # in the task table): makes actor nodes reconstructable
                # from state.list_tasks like plain tasks.
                spec.timing["finished"] = time.time()
                self.events.record(
                    spec.display_name(), t0, time.monotonic(),
                    node.node_id, spec.task_id.hex(),
                    timing=spec.timing, trace_id=spec.trace_id,
                    deps=spec.dep_ids())
            except BaseException as e:  # noqa: BLE001
                box["err"] = e
            finally:
                done.set()

        spec.actor_placement_cb = on_placed  # type: ignore[attr-defined]
        self.scheduler.submit(spec)
        done.wait()
        if "err" in box:
            if name:
                with self._actors_lock:
                    scoped = self._scoped_by_actor.pop(actor_id, None)
                    if scoped and self._named_actors.get(scoped) == actor_id:
                        del self._named_actors[scoped]
            raise box["err"]
        return actor_id

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args, kwargs, opts: Dict[str, Any]) -> Any:
        deadline = time.monotonic() + 5.0
        while True:
            with self._actors_lock:
                st = self._actors.get(actor_id)
            if st is not None:
                break
            # A name reservation may exist before placement completes
            # (get_if_exists race) — give creation a moment.
            if time.monotonic() > deadline:
                raise ActorDiedError(actor_id.hex())
            time.sleep(0.005)
        if st.dead.is_set():
            cause = st.death_cause
            raise (cause if isinstance(cause, ActorDiedError)
                   else ActorDiedError(actor_id.hex()))
        task_id = TaskID.for_actor_task(actor_id)
        # @method(...) defaults; call-site .options(...) wins. Resolved
        # through st.method_defaults so cross-driver proxies (whose cls
        # is a stub) keep the decorated behavior.
        _mdefaults = st.method_defaults.get(method_name, {})
        num_returns = opts.get("num_returns",
                               _mdefaults.get("num_returns", 1))
        # Validate the concurrency group BEFORE any registration —
        # lineage/generator entries must not leak for a rejected call.
        group = opts.get("concurrency_group",
                         _mdefaults.get("concurrency_group"))
        if group is not None and group not in st.group_mailboxes:
            raise ValueError(
                f"Unknown concurrency group {group!r}; declared: "
                f"{sorted(st.concurrency_groups)}")
        streaming = num_returns in ("streaming", "dynamic")
        n_rets = 0 if streaming else num_returns
        spec = TaskSpec(
            task_id=task_id, task_type=TaskType.ACTOR_TASK,
            descriptor=FunctionDescriptor(
                st.cls.__module__, f"{st.cls.__qualname__}.{method_name}"),
            args=tuple(args), kwargs=dict(kwargs),
            num_returns=num_returns,
            resources=ResourceSet({}),
            return_ids=[ObjectID.for_return(task_id, i) for i in range(n_rets)],
            actor_id=actor_id, method_name=method_name,
            name=opts.get("name", ""),
        )
        if streaming:
            gst = _GeneratorState()
            self._generators[task_id] = gst
        with _tracing.span(f"submit:{spec.display_name()}",
                           "task_submit", task_id=task_id.hex()) as sid:
            spec.trace_id = _tracing.current_trace_id()
            spec.parent_span_id = sid
            spec.timing["submitted"] = time.time()
            self._record_lineage(spec)
            with self._pending_lock:
                self._pending_tasks[task_id] = spec
            # Queued = handed to the actor's mailbox (actor calls bypass
            # the scheduler; the mailbox IS their queue).
            spec.timing["queued"] = time.time()
            # Concurrency-group routing (validated above). Actors without
            # dedicated group pools (proc/async) collapse groups into the
            # single ordered mailbox.
            if group is not None and st._group_pools():
                st.group_mailboxes[group].put(spec)
            else:
                st.mailbox.put(spec)
        if streaming:
            return ObjectRefGenerator(task_id, gst)
        refs = [self.register_ref(ObjectRef(oid)) for oid in spec.return_ids]
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def get_actor(self, name: str,
                  namespace: "Optional[str]" = None) -> ActorID:
        ns = namespace or self.namespace
        scoped = f"{ns}/{name}"
        with self._actors_lock:
            aid = self._named_actors.get(scoped)
        if aid is not None:
            return aid
        # Cluster mode: another driver may own the named actor — look
        # it up in the control plane's actor table and attach a proxy
        # (reference: cross-job named-actor lookup via the GCS).
        if self.remote_plane is not None:
            aid = self.remote_plane.attach_named_actor(scoped)
            if aid is not None:
                return aid
        raise ValueError(
            f"Failed to look up actor with name {name!r} in "
            f"namespace {ns!r}")

    def actor_state(self, actor_id: ActorID) -> Optional[ActorState]:
        with self._actors_lock:
            return self._actors.get(actor_id)

    def kill_actor(self, actor_id: ActorID, *, no_restart: bool = True):
        with self._actors_lock:
            st = self._actors.get(actor_id)
        if st is not None:
            st.kill(no_restart=no_restart)

    def register_in_actor_table(self, st: "ActorState",
                                scoped_name: str) -> None:
        """(Re)register an actor's location + metadata in the control
        plane's actor table — the ONE place the table schema lives
        (creation and restart-refresh both come through here).
        Raises AlreadyExistsError when the name belongs to a different
        live actor."""
        import json as _json

        meta = {
            "node_id": st.node.node_id,
            "class": st.cls.__name__,
            "detached": st.detached,
            # so cross-driver proxies keep @method defaults and
            # declared concurrency groups
            "method_defaults": st.method_defaults,
            "concurrency_groups": st.concurrency_groups,
        }
        # Preserve the incarnation counter across re-registrations:
        # daemon adoption fences its KV claims on it, and a refresh
        # that reset it to 0 would make every future claim collide
        # with a spent key (reconstruction permanently stuck).
        try:
            prev = self.remote_plane.control.get_actor(
                st.actor_id.hex())
            inc = _json.loads(prev.get("meta") or "{}").get(
                "incarnation")
            if inc is not None:
                meta["incarnation"] = int(inc)
        except Exception:  # noqa: BLE001 — first registration
            pass
        self.remote_plane.control.register_actor(
            st.actor_id.hex(), name=scoped_name,
            meta=_json.dumps(meta))
        self.remote_plane.control.update_actor(st.actor_id.hex(),
                                               "ALIVE")
        st._cp_registered = True

    def _on_actor_dead(self, st: ActorState):
        self.scheduler.release(st.node.node_id, st.resources)
        with self._actors_lock:
            scoped = self._scoped_by_actor.pop(st.actor_id, None)
            if scoped and self._named_actors.get(scoped) == st.actor_id:
                del self._named_actors[scoped]
        if getattr(st, "_cp_registered", False) and \
                self.remote_plane is not None:
            try:
                self.remote_plane.control.update_actor(
                    st.actor_id.hex(), "DEAD")
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------
    # Dispatch & execution (normal tasks)
    # ------------------------------------------------------------------
    def _run_guarded(self, fn, spec: TaskSpec, node) -> None:
        """Executor entry point: pool futures are never awaited, so an
        exception escaping the execution machinery would vanish into the
        Future and strand the task's returns (driver hang). Contain it
        as a stored TaskError instead."""
        try:
            fn(spec, node)
        except BaseException as e:  # noqa: BLE001
            self._fail_spec_internal(spec, e)

    def _dispatch(self, spec: TaskSpec, node: NodeState):
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            # Resources stay held by the actor until death.
            spec.actor_placement_cb(node)  # type: ignore[attr-defined]
            return
        if node.is_remote:
            fut = node.executor.submit(
                self._run_guarded, self.remote_plane.execute_remote,
                spec, node)

            # Node death shuts the executor with cancel_futures=True:
            # granted-but-unstarted tasks would otherwise vanish (refs
            # never resolve). Requeue them — their charge is released
            # and the scheduler places them on a survivor.
            def _requeue_if_cancelled(f, spec=spec, node=node):
                if not f.cancelled() or self._shutdown:
                    return
                self.scheduler.release_task(spec, node.node_id)
                self._submit_when_ready(spec)

            fut.add_done_callback(_requeue_if_cancelled)
            return
        if isinstance(node, ProcNodeState):
            node.executor.submit(self._run_guarded, self._execute_proc,
                                 spec, node)
            return
        node.executor.submit(self._run_guarded, self._execute, spec, node)

    # ------------------------------------------------------------------
    # Out-of-process execution (worker_proc.py plane)
    # ------------------------------------------------------------------
    def _pack_arg(self, v):
        """Top-level ObjectRef → wire marker (shm key or serialized
        bytes); plain values pass through (cloudpickled with the task)."""
        from .worker_proc import SerArg, ShmArg

        if not isinstance(v, ObjectRef):
            return v
        while True:
            stored = self.store.get_if_exists(v.id())
            if stored is None:
                self._require_recoverable(v.id())
                self._maybe_reconstruct([v.id()])
                stored = self.store.get([v.id()], timeout=None)[0]
            d = stored.data
            if isinstance(d, _ShmMarker):
                if self.shm is not None and self.shm.contains(d.key):
                    return ShmArg(d.key, stored.is_error)
                if ((d.node_id is not None
                        or getattr(d, "locations", None))
                        and self.remote_plane is not None):
                    # Remote-located (multi-host plane): pull it into
                    # the local arena for the local worker.
                    try:
                        self.remote_plane.ensure_local(d)
                        return ShmArg(d.key, stored.is_error)
                    except KeyError:
                        pass  # source node gone — reconstruct below
                self._require_recoverable(v.id())
                self.store.delete([v.id()])  # evicted — reconstruct
                self._maybe_reconstruct([v.id()])
                continue
            return SerArg(d.to_bytes(), stored.is_error)

    def _require_recoverable(self, oid: ObjectID) -> None:
        """Fail fast (like Runtime.get) instead of blocking forever on an
        object that can never come back: no lineage and not in flight."""
        with self.lineage_lock:
            if oid in self.lineage:
                return
        with self._pending_lock:
            if any(oid in t.return_ids for t in self._pending_tasks.values()):
                return
        raise ObjectLostError(
            f"object {oid.hex()[:16]} evicted and not reconstructable "
            "(no lineage)")

    def _pack_task_msg(self, spec: TaskSpec, worker) -> Dict[str, Any]:
        import cloudpickle

        streaming = spec.num_returns in ("streaming", "dynamic")
        fid = spec.descriptor.function_id
        msg = {
            "type": "task", "task_id": spec.task_id, "fid": fid,
            "args": tuple(self._pack_arg(a) for a in spec.args),
            "kwargs": {k: self._pack_arg(v)
                       for k, v in spec.kwargs.items()},
            "num_returns": 0 if streaming else spec.num_returns,
            "return_ids": [oid.binary() for oid in spec.return_ids],
            "streaming": streaming,
        }
        if spec.trace_id:
            # Trace propagation across the process boundary: the worker
            # re-enters this trace, parented to the driver-side span
            # active at pack time (the execute span).
            msg["trace_id"] = spec.trace_id
            msg["parent_span_id"] = (_tracing.current_span_id()
                                     or spec.parent_span_id)
        if streaming and spec.task_id in self._generators:
            # Only with a LIVE consumer: reconstruction re-runs have
            # nobody sending credits — a watermark would deadlock them.
            msg["backpressure"] = config.generator_backpressure_max_items
        if spec.runtime_env:
            msg["runtime_env"] = spec.runtime_env
        if fid not in worker.exported_fns:
            msg["fn"] = cloudpickle.dumps(
                self.function_manager.get(fid))
        return msg

    def _store_packed(self, oid: ObjectID, packed,
                      node_id: Optional[str] = None):
        """Store a worker-produced ('shm'|'ser', payload) wire value.
        node_id = which node's arena holds a 'shm' payload (None =
        the local arena)."""
        kind, payload = packed
        if kind == "shm":
            # Worker already wrote the bytes under the return id.
            self.store.put(oid, _ShmMarker(payload, node_id=node_id))
        else:
            self.store.put(
                oid, serialization.SerializedObject.from_bytes(payload))
        get_recorder().record(
            "object_transfer", "result_stored",
            object_id=oid.hex()[:16], kind=kind,
            node=node_id or "local")

    def _unpack_error(self, packed) -> BaseException:
        _, payload = packed
        return serialization.deserialize(
            serialization.SerializedObject.from_bytes(payload))

    def _memory_victims(self):
        """Running out-of-process tasks as OOM-kill candidates:
        (submit_order, retriable, kill_cb, label). kill_cb re-validates
        under the lock that the task still owns that worker — between
        the snapshot and the kill the task may finish and the worker be
        re-leased to an innocent (possibly non-retriable) task."""
        with self._running_lock:
            entries = list(self._running_proc.items())
        out = []
        for task_id, (seq, spec, worker) in entries:
            retriable = (spec.retries_left > 0
                         and spec.num_returns not in ("streaming",
                                                      "dynamic"))

            def kill(task_id=task_id, seq=seq, worker=worker):
                with self._running_lock:
                    cur = self._running_proc.get(task_id)
                    if cur is None or cur[0] != seq or cur[2] is not worker:
                        return  # task already finished; worker re-leased
                    worker.kill()

            out.append((seq, retriable, kill, spec.display_name()))
        return out

    def _maybe_retry_system(self, spec: TaskSpec, e: BaseException) -> bool:
        """Worker-process death: always retryable while retries remain
        (reference: system failures consume max_retries regardless of
        retry_exceptions, task_manager.h)."""
        if spec.num_returns in ("streaming", "dynamic"):
            return False  # partial stream already delivered
        if spec.retries_left <= 0:
            return False
        spec.retries_left -= 1
        logger.warning("Worker died running %s; retrying (%d left): %s",
                       spec.display_name(), spec.retries_left, e)
        with self._pending_lock:
            self._pending_tasks[spec.task_id] = spec
        self._submit_when_ready(spec)
        return True

    def _execute_proc(self, spec: TaskSpec, node: "ProcNodeState"):
        from .worker_proc import WorkerCrashedError

        t0 = time.monotonic()
        spec.timing["running"] = time.time()
        retried = False
        failed = False
        worker = None
        ran_on_worker = False
        streaming = spec.num_returns in ("streaming", "dynamic")
        gst = self._generators.get(spec.task_id) if streaming else None
        # Re-enter the submission trace so the driver-side execute span
        # (and via _pack_task_msg, the worker-side spans) link to it.
        trace_cm = contextlib.ExitStack()
        if spec.trace_id:
            trace_cm.enter_context(_tracing.trace_context(
                spec.trace_id, spec.parent_span_id))
            trace_cm.enter_context(_tracing.span(
                f"execute:{spec.display_name()}", "task_execute",
                task_id=spec.task_id.hex(), node=node.node_id))
        try:
            if spec.task_id in self._cancelled:
                raise TaskCancelledError(spec.display_name())
            worker = node.pool.acquire(timeout=60)
            with self._running_lock:
                self._running_seq += 1
                self._running_proc[spec.task_id] = (
                    self._running_seq, spec, worker)
            msg = self._pack_task_msg(spec, worker)

            def on_stream(item):
                # gst is None when the task is a lineage RECONSTRUCTION
                # of an evicted stream item: re-store the items (that is
                # the point), nobody holds a live generator.
                oid = ObjectID.for_return(spec.task_id, item["index"])
                with self.lineage_lock:
                    self.lineage[oid] = spec
                self._store_packed(oid, item["payload"])
                if gst is not None:
                    ref = self.register_ref(ObjectRef(oid))
                    with gst.cv:
                        gst.refs.append(ref)
                        gst.cv.notify_all()

            ran_on_worker = True  # run_task reached the worker
            if gst is not None:
                with gst.cv:
                    gst.ack_cb = worker.send_ack
            try:
                reply = worker.run_task(
                    msg, on_stream=on_stream if streaming else None)
            finally:
                if gst is not None:
                    with gst.cv:
                        gst.ack_cb = None
            worker.exported_fns.add(msg["fid"])
            # Merge worker-side spans BEFORE the error check — a failed
            # task's trace is the one someone will actually read.
            for ev in reply.get("spans") or ():
                self.events.record_raw(ev)
            if reply.get("error") is not None:
                raise self._unpack_error(reply["error"])
            if streaming and gst is not None:
                with gst.cv:
                    gst.done = True
                    gst.cv.notify_all()
                self._generators.pop(spec.task_id, None)
            else:
                for oid, packed in zip(spec.return_ids, reply["returns"]):
                    self._store_packed(oid, packed)
        except WorkerCrashedError as e:
            retried = self._maybe_retry_system(spec, e)
            rec = get_recorder()
            rec.record("scheduler", "worker_crashed",
                       task=spec.display_name(),
                       task_id=spec.task_id.hex(), node=node.node_id,
                       retried=retried)
            if not retried:
                failed = True
                self._store_error(spec, _wrap(spec, e), t0)
                rec.auto_dump("worker_crashed",
                              crash_pid=getattr(worker, "pid", None))
        except BaseException as e:  # noqa: BLE001
            retried = self._maybe_retry(spec, e)
            if not retried:
                failed = True
                self._store_error(spec, _wrap(spec, e), t0)
        finally:
            trace_cm.close()
            with self._running_lock:
                self._running_proc.pop(spec.task_id, None)
            if worker is not None:
                # Count only calls that actually reached the worker —
                # pre-execution failures (arg packing etc.) must not
                # burn max_calls budget.
                fid = spec.descriptor.function_id
                if ran_on_worker:
                    worker.fn_calls[fid] = worker.fn_calls.get(fid, 0) + 1
                if (ran_on_worker and spec.max_calls > 0
                        and worker.fn_calls[fid] >= spec.max_calls):
                    # max_calls: retire this worker process (the pool
                    # respawns a fresh one in the background) — bounds
                    # state leaked by the user function.
                    node.pool.recycle(worker)
                else:
                    node.pool.release(worker)
            if not retried:
                spec.timing["finished"] = time.time()
                self._task_finished(spec)
                record_task_metrics(
                    spec.timing, "FAILED" if failed else "FINISHED")
            self.scheduler.release_task(spec, node.node_id)
            self.events.record(
                spec.display_name(), t0, time.monotonic(),
                node.node_id, spec.task_id.hex(),
                timing=spec.timing, trace_id=spec.trace_id,
                deps=spec.dep_ids(), returns=spec.return_hexes())

    def _execute(self, spec: TaskSpec, node: NodeState):
        t0 = time.monotonic()
        spec.timing["running"] = time.time()
        prev_task, prev_node = _ctx.task_id, _ctx.node_id
        _ctx.task_id, _ctx.node_id = spec.task_id, node.node_id
        retried = False
        failed = False
        trace_cm = contextlib.ExitStack()
        if spec.trace_id:
            trace_cm.enter_context(_tracing.trace_context(
                spec.trace_id, spec.parent_span_id))
            trace_cm.enter_context(_tracing.span(
                f"execute:{spec.display_name()}", "task_execute",
                task_id=spec.task_id.hex(), node=node.node_id))
        try:
            if spec.task_id in self._cancelled:
                raise TaskCancelledError(spec.display_name())
            func = self.function_manager.get(spec.descriptor.function_id)
            args, kwargs = self._materialize_args(spec)
            with _renv_applied(spec.runtime_env):
                result = func(*args, **kwargs)
            self._store_results(spec, result, t0)
        except BaseException as e:  # noqa: BLE001
            retried = self._maybe_retry(spec, e)
            if not retried:
                failed = True
                self._store_error(spec, _wrap(spec, e), t0)
        finally:
            trace_cm.close()
            _ctx.task_id, _ctx.node_id = prev_task, prev_node
            if not retried:
                spec.timing["finished"] = time.time()
                self._task_finished(spec)
                record_task_metrics(
                    spec.timing, "FAILED" if failed else "FINISHED")
            self.scheduler.release_task(spec, node.node_id)
            self.events.record(
                spec.display_name(), t0, time.monotonic(),
                node.node_id, spec.task_id.hex(),
                timing=spec.timing, trace_id=spec.trace_id,
                deps=spec.dep_ids(), returns=spec.return_hexes())

    def _maybe_retry(self, spec: TaskSpec, e: BaseException) -> bool:
        if isinstance(e, (TaskCancelledError, _ActorExit)):
            return False
        retry_on_app_error = (
            spec.retry_exceptions is True
            or (isinstance(spec.retry_exceptions, (list, tuple))
                and isinstance(e, tuple(spec.retry_exceptions)))
        )
        if not retry_on_app_error or spec.retries_left <= 0:
            return False
        spec.retries_left -= 1
        logger.warning(
            "Task %s failed (%s); retrying (%d left).",
            spec.display_name(), type(e).__name__, spec.retries_left)
        if config.task_retry_delay_ms:
            time.sleep(config.task_retry_delay_ms / 1000)
        with self._pending_lock:
            self._pending_tasks[spec.task_id] = spec
        self._submit_when_ready(spec)
        return True

    def _materialize_args(self, spec: TaskSpec):
        """Resolve top-level ObjectRef args (error-poisoning included)."""
        def resolve(v):
            if isinstance(v, ObjectRef):
                while True:
                    stored = self.store.get_if_exists(v.id())
                    if stored is None:
                        # Dependency lost between readiness and execution.
                        self._maybe_reconstruct([v.id()])
                        stored = self.store.get([v.id()], timeout=None)[0]
                    try:
                        data = self._load_data(stored)
                    except KeyError:  # shm copy evicted — reconstruct
                        self.store.delete([v.id()])
                        self._maybe_reconstruct([v.id()])
                        continue
                    value = serialization.deserialize(data)
                    if stored.is_error:
                        raise value
                    return value
            return v

        args = tuple(resolve(a) for a in spec.args)
        kwargs = {k: resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    def _store_results(self, spec: TaskSpec, result: Any, t0: float):
        if spec.num_returns in ("streaming", "dynamic"):
            self._consume_generator(spec, result)
            return
        n = spec.num_returns
        if n == 0:
            return
        values = (result,) if n == 1 else tuple(result)
        if n > 1 and len(values) != n:
            err = _wrap(spec, ValueError(
                f"Task {spec.display_name()} declared num_returns={n} but "
                f"returned {len(values)} values"))
            self._store_error(spec, err, t0)
            return
        for oid, v in zip(spec.return_ids, values):
            self._store(oid, serialization.serialize(v))

    def _consume_generator(self, spec: TaskSpec, gen):
        # Reconstruction re-runs have no live consumer: use a throwaway
        # state so the items still get re-stored.
        live = self._generators.get(spec.task_id)
        st = live or _GeneratorState()
        bp = config.generator_backpressure_max_items
        i = 0
        try:
            for item in gen:
                oid = ObjectID.for_return(spec.task_id, i)
                with self.lineage_lock:
                    self.lineage[oid] = spec
                self._store(oid, serialization.serialize(item))
                ref = self.register_ref(ObjectRef(oid))
                with st.cv:
                    st.refs.append(ref)
                    st.cv.notify_all()
                    # Pause the producer while the consumer lags
                    # (reference: GeneratorWaiter backpressure). Only
                    # for live consumers — a reconstruction run just
                    # re-stores.
                    if bp > 0 and live is not None:
                        while (len(st.refs) - st.consumed >= bp
                               and not st.abandoned
                               and spec.task_id not in self._cancelled):
                            st.cv.wait(timeout=0.5)
                i += 1
        except BaseException as e:  # noqa: BLE001
            oid = ObjectID.for_return(spec.task_id, i)
            self._store(oid, serialization.serialize(_wrap(spec, e)),
                        is_error=True)
            ref = self.register_ref(ObjectRef(oid))
            with st.cv:
                st.refs.append(ref)
                st.cv.notify_all()
        finally:
            with st.cv:
                st.done = True
                st.cv.notify_all()
            # The consumer's ObjectRefGenerator holds the state directly;
            # drop the table entry so streaming calls don't accumulate.
            self._generators.pop(spec.task_id, None)

    def _fail_spec_internal(self, spec: TaskSpec, exc: BaseException):
        """Last-resort completion for a task the machinery itself failed
        on (reference: task_manager.h:195 — every pending task completes,
        whatever kills it). An exception escaping the executor/mailbox/
        retry/store path would otherwise leave the return IDs forever
        pending and `ray.get` hung (VERDICT r4 weak #2). Stores a
        TaskError on all return IDs (or the generator stream), marks the
        task finished, and NEVER raises.
        """
        try:
            logger.error(
                "Internal error while completing task %s — failing its "
                "returns: %r", spec.display_name(), exc, exc_info=exc)
        except Exception:  # noqa: BLE001
            pass
        err = exc if isinstance(exc, TaskError) else TaskError(
            spec.display_name(),
            RuntimeError(f"ray_tpu internal error: {exc!r}"))
        try:
            rec = get_recorder()
            rec.record("scheduler", "task_internal_failure",
                       task=spec.display_name(),
                       task_id=spec.task_id.hex(), error=repr(exc)[:200])
            rec.auto_dump("task_internal_failure")
        except Exception:  # noqa: BLE001 - recorder must not block failing
            pass
        try:
            self._store_error(spec, err)
        except BaseException:  # noqa: BLE001 - e.g. err unpicklable
            try:
                fallback = TaskError(spec.display_name(), RuntimeError(
                    f"ray_tpu internal error (unstorable cause "
                    f"{type(exc).__name__})"))
                data = serialization.serialize(fallback)
                for oid in spec.return_ids:
                    self._store(oid, data, is_error=True)
            except BaseException:  # noqa: BLE001
                logger.critical(
                    "Could not store internal error for task %s; gets on "
                    "its returns may hang", spec.display_name())
        try:
            self._task_finished(spec)
        except BaseException:  # noqa: BLE001
            pass

    def _store_error(self, spec: TaskSpec, err: BaseException,
                     t0: Optional[float] = None):
        data = serialization.serialize(err)
        ids = spec.return_ids
        if spec.num_returns in ("streaming", "dynamic"):
            st = self._generators.pop(spec.task_id, None)
            if st is not None:
                oid = ObjectID.for_return(spec.task_id, len(st.refs))
                self._store(oid, data, is_error=True)
                ref = self.register_ref(ObjectRef(oid))
                with st.cv:
                    st.refs.append(ref)
                    st.done = True
                    st.cv.notify_all()
            return
        for oid in ids:
            self._store(oid, data, is_error=True)

    # ------------------------------------------------------------------
    # Cancellation
    # ------------------------------------------------------------------
    def cancel(self, ref: ObjectRef, *, force: bool = False):
        task_id = ref.task_id()
        self._cancelled.add(task_id)
        if self.scheduler.cancel(task_id):
            with self._pending_lock:
                spec = self._pending_tasks.pop(task_id, None)
            if spec is not None:
                self._store_error(
                    spec, TaskCancelledError(spec.display_name()))

    # ------------------------------------------------------------------
    # Lineage reconstruction
    # ------------------------------------------------------------------
    def _task_finished(self, spec: TaskSpec):
        with self._pending_lock:
            self._pending_tasks.pop(spec.task_id, None)

    def _maybe_reconstruct(self, ids: Sequence[ObjectID]):
        """Resubmit creating tasks for objects that are lost (not stored,
        not pending). Recursive through the lineage graph
        (reference: object_recovery_manager.h:96-106)."""
        for oid in ids:
            if self.store.contains(oid):
                continue
            with self.lineage_lock:
                spec = self.lineage.get(oid)
            if spec is None:
                continue  # put object or unknown → will block / timeout
            with self._pending_lock:
                if spec.task_id in self._pending_tasks:
                    continue  # already in flight
                # Completion stores results BEFORE un-pending the task,
                # so not-pending + stored means it finished between the
                # contains check above and here — without this re-check
                # that window resubmits a finished task (observed as
                # double execution under RAY_TPU_LOCKTRACE).
                if self.store.contains(oid):
                    continue
                self._pending_tasks[spec.task_id] = spec
            if spec.is_actor_task():
                # Actor-task returns are only recomputable while the actor
                # lives (reference: actor lineage is not reconstructed).
                st = self.actor_state(spec.actor_id)
                if st is not None and not st.dead.is_set():
                    st.mailbox.put(spec)
                else:
                    self._task_finished(spec)
                continue
            logger.info("Reconstructing object %s via task %s",
                        oid.hex()[:16], spec.display_name())
            # Recursively ensure arg lineage first.
            dep_ids = [a.id() for a in spec.args if isinstance(a, ObjectRef)]
            dep_ids += [v.id() for v in spec.kwargs.values()
                        if isinstance(v, ObjectRef)]
            if dep_ids:
                self._maybe_reconstruct(dep_ids)
            self._submit_when_ready(spec)

    def delete_objects(self, refs: Sequence[ObjectRef]):
        """Evict objects from the store (keeps lineage → reconstructable)."""
        self.store.delete([r.id() for r in refs])

    # ------------------------------------------------------------------
    # Introspection / shutdown
    # ------------------------------------------------------------------
    def cluster_resources(self) -> Dict[str, float]:
        total = ResourceSet({})
        for n in self.scheduler.nodes():
            total = total.add(n.total)
        return total.to_dict()

    def available_resources(self) -> Dict[str, float]:
        total = ResourceSet({})
        for n in self.scheduler.nodes():
            total = total.add(n.available)
        return total.to_dict()

    def timeline(self) -> List[dict]:
        return self.events.dump()

    def shutdown(self):
        self._shutdown = True
        try:
            from ..observability import continuous as _contmod
            from ..observability import tsdb as _tsdbmod

            if self._contprof is not None:
                _contmod.stop_continuous_profiler()
                self._contprof = None
            if self._tsdb is not None:
                _tsdbmod.stop_scraper()
                self._tsdb = None
            if self._ledger is not None:
                from ..observability import ledger as _ledgermod

                _ledgermod.stop_ledger()
                self._ledger = None
        except Exception:  # noqa: BLE001
            pass
        if self.memory_monitor is not None:
            self.memory_monitor.stop()
            self.memory_monitor = None
        if self.remote_plane is not None:
            try:
                self.remote_plane.shutdown()
            except Exception:  # noqa: BLE001
                pass
        if self.log_monitor is not None:
            try:
                self.log_monitor.stop()
            except Exception:  # noqa: BLE001
                pass
            self.log_monitor = None
        from .._private import session as _session

        _session.clear_session()
        self._gc_queue.put(None)
        # The GC thread touches the shm mapping — it must finish before
        # munmap, or a queued delete dereferences unmapped memory.
        self._gc_thread.join(timeout=5)
        if self._gc_thread.is_alive():
            # A stuck GC thread (e.g. waiting on the process-shared mutex
            # of a crashed peer) still references the mapping — leak it
            # rather than munmap under its feet.
            self.shm = None
        if self.shm is not None:
            try:
                self.shm.close()
                type(self.shm).unlink(self._shm_name)
            except Exception:  # noqa: BLE001
                pass
            self.shm = None
        with self._actors_lock:
            actors = list(self._actors.values())
        for st in actors:
            # Detached actors survive their driver (reference
            # lifetime="detached" semantics) — but only on the daemon
            # plane; an in-process actor cannot outlive this process,
            # so skipping its kill would only leak threads.
            if not (getattr(st, "detached", False)
                    and st.node.is_remote):
                st.kill()
        for node in self.scheduler.nodes():
            node.shutdown()


# ---------------------------------------------------------------------------
# Globals
# ---------------------------------------------------------------------------

_global_runtime: Optional[Runtime] = None
_global_lock = threading.Lock()


def init_runtime(**kwargs) -> Runtime:
    global _global_runtime
    with _global_lock:
        if _global_runtime is not None:
            return _global_runtime
        _global_runtime = Runtime(**kwargs)
        return _global_runtime


def global_runtime() -> Runtime:
    rt = _global_runtime
    if rt is None:
        return init_runtime()
    return rt


def global_runtime_or_none() -> Optional[Runtime]:
    return _global_runtime


def shutdown_runtime():
    global _global_runtime
    with _global_lock:
        if _global_runtime is not None:
            _global_runtime.shutdown()
            _global_runtime = None


def is_initialized() -> bool:
    return _global_runtime is not None
