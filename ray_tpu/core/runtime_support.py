"""Runtime support: execution context, task events, function
distribution, streaming generators.

Split out of core/runtime.py (VERDICT r3 #9 — the fused
CoreWorker+raylet file was growing without bound); every name is
re-exported from runtime for compatibility. Reference capabilities:
runtime_context.py, task_event_buffer.h, _private/function_manager.py,
ObjectRefGenerator (_raylet.pyx:272).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .._private.config import config
from .exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
)
from .ids import ActorID, JobID, ObjectID, TaskID
from .object_ref import ObjectRef
from .task import FunctionDescriptor

logger = logging.getLogger("ray_tpu")


def global_runtime():
    # Lazy: runtime.py imports this module at load time, so the real
    # global_runtime can only be resolved after both modules exist.
    from .runtime import global_runtime as _gr
    return _gr()

# ---------------------------------------------------------------------------
# Runtime context (per-thread execution info)
# ---------------------------------------------------------------------------

class _ExecCtx(threading.local):
    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.actor_id: Optional[ActorID] = None
        self.node_id: Optional[str] = None
        self.put_index: int = 0


_ctx = _ExecCtx()


class RuntimeContext:
    """Public runtime-context view (reference: python/ray/runtime_context.py)."""

    @property
    def job_id(self) -> JobID:
        return global_runtime().job_id

    def get_task_id(self) -> Optional[str]:
        return _ctx.task_id.hex() if _ctx.task_id else None

    def get_actor_id(self) -> Optional[str]:
        return _ctx.actor_id.hex() if _ctx.actor_id else None

    def get_node_id(self) -> Optional[str]:
        # Daemon workers learn their host daemon's id from the spawn
        # env (reference: runtime_context reporting the raylet's node).
        return (_ctx.node_id or os.environ.get("RAY_TPU_NODE_ID")
                or global_runtime().head_node_id)


# ---------------------------------------------------------------------------
# Task events / timeline
# ---------------------------------------------------------------------------

class TaskEventBuffer:
    """Chrome-trace-compatible task event ring
    (reference: src/ray/core_worker/task_event_buffer.h → `ray timeline`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def record(self, name: str, phase_start: float, phase_end: float,
               node_id: str, task_id: str, category: str = "task",
               *, timing: Optional[Dict[str, float]] = None,
               trace_id: Optional[str] = None,
               deps: Optional[List[str]] = None,
               returns: Optional[List[str]] = None):
        ev = {
            "name": name, "cat": category, "ph": "X",
            "ts": phase_start * 1e6, "dur": (phase_end - phase_start) * 1e6,
            "pid": node_id, "tid": task_id,
        }
        if timing or trace_id or deps or returns:
            args: Dict[str, Any] = {}
            if timing:
                args["timing"] = dict(timing)
            if trace_id:
                args["trace_id"] = trace_id
            # object-graph stamps: dep/return ids let state.list_tasks
            # reconstruct the dynamic task graph after the fact
            if deps:
                args["deps"] = list(deps)
            if returns:
                args["returns"] = list(returns)
            ev["args"] = args
        self.record_raw(ev)

    def record_raw(self, ev: dict) -> None:
        """Append a pre-built chrome-trace event (tasks + tracing spans).
        Honors the enable_timeline gate."""
        if not config.enable_timeline:
            return
        with self._lock:
            if len(self._events) >= config.task_event_buffer_max:
                self._events.pop(0)
            self._events.append(ev)

    def dump(self) -> List[dict]:
        with self._lock:
            return list(self._events)


# ---------------------------------------------------------------------------
# Function manager
# ---------------------------------------------------------------------------

class FunctionManager:
    """Function registry (reference: python/ray/_private/function_manager.py
    — exports pickled functions to GCS KV; workers import lazily). Local
    mode keeps the callables; the multiprocess runtime ships pickles."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fns: Dict[bytes, Callable] = {}

    def register(self, func: Callable) -> FunctionDescriptor:
        fid = uuid.uuid4().bytes
        with self._lock:
            self._fns[fid] = func
        return FunctionDescriptor(
            module=getattr(func, "__module__", "<unknown>") or "<unknown>",
            qualname=getattr(func, "__qualname__", repr(func)),
            function_id=fid,
        )

    def get(self, fid: bytes) -> Callable:
        with self._lock:
            return self._fns[fid]


# ---------------------------------------------------------------------------
# Streaming generators
# ---------------------------------------------------------------------------

class _GeneratorState:
    def __init__(self):
        self.cv = threading.Condition()
        self.refs: List[ObjectRef] = []
        self.done = False
        # Backpressure (reference: GeneratorWaiter, core_worker.h):
        # `consumed` advances as the iterator hands out refs; producers
        # pause while produced − consumed exceeds the watermark.
        # `ack_cb` (set while an out-of-process producer is streaming)
        # forwards consumption credits to the producing worker; call it
        # under `cv` — the producer side clears it under the same lock.
        self.consumed = 0
        self.ack_cb = None
        self.abandoned = False


class ObjectRefGenerator:
    """Streaming-returns iterator
    (reference: python/ray/_raylet.pyx:272 ObjectRefGenerator): yields
    ObjectRefs as the remote generator produces them; consumption feeds
    producer backpressure (generator_backpressure_max_items); also
    usable as an async iterator."""

    def __init__(self, task_id: TaskID, state: _GeneratorState):
        self._task_id = task_id
        self._state = state
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        st = self._state
        with st.cv:
            while len(st.refs) <= self._i and not st.done:
                st.cv.wait()
            if len(st.refs) > self._i:
                ref = st.refs[self._i]
                self._i += 1
                if self._i > st.consumed:
                    st.consumed = self._i
                    if st.ack_cb is not None:
                        st.ack_cb(1)
                    st.cv.notify_all()
                return ref
            raise StopIteration

    def __del__(self):
        # Consumer gone: release any paused producer for good.
        st = getattr(self, "_state", None)
        if st is None:
            return
        try:
            with st.cv:
                st.abandoned = True
                if st.ack_cb is not None:
                    st.ack_cb(1 << 20)
                st.cv.notify_all()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def __aiter__(self):
        return self

    async def __anext__(self):
        # StopIteration can't cross a Future boundary (asyncio converts it
        # to RuntimeError) — use a sentinel instead.
        import asyncio
        loop = asyncio.get_running_loop()
        sentinel = object()

        def step():
            try:
                return self.__next__()
            except StopIteration:
                return sentinel

        item = await loop.run_in_executor(None, step)
        if item is sentinel:
            raise StopAsyncIteration
        return item

    def completed(self) -> List[ObjectRef]:
        with self._state.cv:
            return list(self._state.refs)

