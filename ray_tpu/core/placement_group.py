"""Placement groups with 2-phase bundle reservation.

Capability-equivalent to the reference's placement groups
(reference: python/ray/util/placement_group.py:41,:146 and the GCS-side
2-phase-commit scheduler src/ray/gcs/gcs_server/gcs_placement_group_*.h,
raylet prepare/commit in placement_group_resource_manager.h):
bundles of resources atomically reserved across nodes under a strategy
(PACK/SPREAD/STRICT_PACK/STRICT_SPREAD), with prepare-all-then-commit
semantics and full rollback on failure. TPU-native: STRICT_PACK onto one
slice is the gang-scheduling primitive for SPMD jobs.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional

from .._private.config import config
from .resources import ResourceSet
from .runtime import global_runtime
from .scheduler import NodeState

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name
        self._bundle_nodes: List[Optional[str]] = [None] * len(bundles)
        # Per-bundle remaining capacity; tasks scheduled into the PG are
        # charged here (the node's capacity was already debited at reserve).
        self._bundle_available: List[ResourceSet] = [
            ResourceSet(b) for b in bundles]
        self._committed = False
        self._ready = threading.Event()
        self._failed: Optional[str] = None

    # -- API parity -------------------------------------------------------
    def ready(self):
        """Returns an ObjectRef that resolves when the PG is placed
        (non-blocking; the wait happens inside a 0-CPU task pinned to
        the DRIVER's node — it waits on this process's in-memory
        placement event and must not ship to a remote daemon)."""
        from .. import remote
        from .runtime import global_runtime
        from .task import NodeAffinitySchedulingStrategy
        pg = self

        @remote(num_cpus=0, scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=global_runtime().head_node_id, soft=False))
        def _pg_ready() -> bool:
            pg.wait(timeout=None)
            return True

        return _pg_ready.remote()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = self._ready.wait(
            timeout if timeout is not None else config.gang_schedule_timeout_s)
        if self._failed:
            raise RuntimeError(
                f"Placement group {self.id} failed: {self._failed}")
        return ok

    def bundle_nodes(self, index: int) -> List[str]:
        if index < 0:
            return [n for n in self._bundle_nodes if n is not None]
        node = self._bundle_nodes[index]
        return [node] if node is not None else []

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}: {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("Placement group requires at least one bundle")
    pg = PlacementGroup(uuid.uuid4().hex[:16], bundles, strategy, name)
    rt = global_runtime()
    # Reserve in a background thread so creation is async (parity: the
    # reference returns immediately; `ready()` awaits placement).
    t = threading.Thread(target=_reserve, args=(rt, pg), daemon=True)
    t.start()
    rt.placement_groups = getattr(rt, "placement_groups", {})
    rt.placement_groups[pg.id] = pg
    return pg


def _live_placement_groups() -> List[PlacementGroup]:
    """All registered PGs of the current runtime (state API)."""
    rt = global_runtime()
    return list(getattr(rt, "placement_groups", {}).values())


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = global_runtime()
    for i, node_id in enumerate(pg._bundle_nodes):
        if node_id is not None:
            rt.scheduler.release(node_id, ResourceSet(pg.bundle_specs[i]))
            pg._bundle_nodes[i] = None
    getattr(rt, "placement_groups", {}).pop(pg.id, None)


def _reserve(rt, pg: PlacementGroup) -> None:
    deadline = time.monotonic() + config.gang_schedule_timeout_s
    while time.monotonic() < deadline:
        if _try_reserve_all(rt, pg):
            pg._ready.set()
            return
        time.sleep(0.02)
    pg._failed = "timed out acquiring bundles"
    pg._ready.set()


def _try_reserve_all(rt, pg: PlacementGroup) -> bool:
    """Phase 1: tentatively subtract every bundle; rollback on any failure.

    The scheduler lock is per-operation, so this loop is the 'prepare' and
    a full rollback is the abort — single-process equivalent of the
    reference's PrepareBundleResources/CommitBundleResources 2PC.
    """
    nodes = [n for n in rt.scheduler.nodes() if n.alive]
    placed: List[tuple] = []

    def rollback():
        for node, rs in placed:
            rt.scheduler.release(node.node_id, rs)

    chosen: List[Optional[NodeState]] = [None] * pg.bundle_count
    used_nodes: set = set()
    for i, spec in enumerate(pg.bundle_specs):
        rs = ResourceSet(spec)
        if pg.strategy == "STRICT_PACK":
            cands = [chosen[0]] if i > 0 and chosen[0] else nodes
        elif pg.strategy == "STRICT_SPREAD":
            cands = [n for n in nodes if n.node_id not in used_nodes]
        elif pg.strategy == "SPREAD":
            fresh = [n for n in nodes if n.node_id not in used_nodes]
            cands = fresh or nodes
        else:  # PACK: prefer already-used nodes
            cands = ([n for n in nodes if n.node_id in used_nodes] +
                     [n for n in nodes if n.node_id not in used_nodes])
        ok = False
        for node in cands:
            if node is None:
                continue
            try:
                # Atomic per-node reserve through the scheduler lock.
                with rt.scheduler._lock:
                    if rs.fits(node.available):
                        # charge() (not a bare subtract) so heartbeat
                        # load reports account for this reservation.
                        node.charge(rs)
                        ok = True
                    else:
                        continue
            except ValueError:
                continue
            placed.append((node, rs))
            chosen[i] = node
            used_nodes.add(node.node_id)
            break
        if not ok:
            rollback()
            return False
    # Phase 2: commit — record bundle→node mapping and open the bundles
    # for task charging.
    for i, node in enumerate(chosen):
        pg._bundle_nodes[i] = node.node_id
    pg._committed = True
    return True
