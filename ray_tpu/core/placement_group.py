"""Placement groups with 2-phase bundle reservation.

Capability-equivalent to the reference's placement groups
(reference: python/ray/util/placement_group.py:41,:146 and the GCS-side
2-phase-commit scheduler src/ray/gcs/gcs_server/gcs_placement_group_*.h,
raylet prepare/commit in placement_group_resource_manager.h):
bundles of resources atomically reserved across nodes under a strategy
(PACK/SPREAD/STRICT_PACK/STRICT_SPREAD), with prepare-all-then-commit
semantics and full rollback on failure. TPU-native: STRICT_PACK onto one
slice is the gang-scheduling primitive for SPMD jobs.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional

from .._private.config import config
from .resources import ResourceSet
from .runtime import global_runtime
from .scheduler import NodeState

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self.name = name
        self._bundle_nodes: List[Optional[str]] = [None] * len(bundles)
        # Per-bundle remaining capacity; tasks scheduled into the PG are
        # charged here (the node's capacity was already debited at reserve).
        self._bundle_available: List[ResourceSet] = [
            ResourceSet(b) for b in bundles]
        self._committed = False
        self._ready = threading.Event()
        self._failed: Optional[str] = None
        # Guards bundle_nodes mutation vs removal: a reserve/repair
        # thread must never commit charges into a PG that was removed
        # while it was looping (the charge would leak forever).
        self._state_lock = threading.Lock()
        # Serializes repair threads: two nodes dying close together
        # must not compute used_nodes concurrently (STRICT_SPREAD
        # would co-locate both replacement bundles).
        self._repair_lock = threading.Lock()
        self._removed = False

    # -- API parity -------------------------------------------------------
    def ready(self):
        """Returns an ObjectRef that resolves when the PG is placed
        (non-blocking; the wait happens inside a 0-CPU task pinned to
        the DRIVER's node — it waits on this process's in-memory
        placement event and must not ship to a remote daemon)."""
        from .. import remote
        from .runtime import global_runtime
        from .task import NodeAffinitySchedulingStrategy
        pg = self

        @remote(num_cpus=0, scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=global_runtime().head_node_id, soft=False))
        def _pg_ready() -> bool:
            pg.wait(timeout=None)
            return True

        return _pg_ready.remote()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until placed. timeout=None waits the gang-schedule
        timeout and RAISES if still unplaced — silently returning False
        would let a gang run against an unplaced group and queue its
        tasks forever."""
        ok = self._ready.wait(
            timeout if timeout is not None else config.gang_schedule_timeout_s)
        if self._failed:
            raise RuntimeError(
                f"Placement group {self.id} failed: {self._failed}")
        if not ok and timeout is None:
            # The reserve thread's deadline is independent of ours: a
            # commit can land exactly at our timeout. Re-check before
            # declaring failure, or a successfully charged placement
            # hides behind a 'never placed' error and leaks.
            if self._ready.is_set():
                if self._failed:
                    raise RuntimeError(
                        f"Placement group {self.id} failed: "
                        f"{self._failed}")
                return True
            raise RuntimeError(
                f"Placement group {self.id} not placed within "
                f"{config.gang_schedule_timeout_s}s "
                f"(bundles={self.bundle_specs}, "
                f"strategy={self.strategy})")
        return ok

    def bundle_nodes(self, index: int) -> List[str]:
        if index < 0:
            return [n for n in self._bundle_nodes if n is not None]
        node = self._bundle_nodes[index]
        return [node] if node is not None else []

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}: {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("Placement group requires at least one bundle")
    pg = PlacementGroup(uuid.uuid4().hex[:16], bundles, strategy, name)
    rt = global_runtime()
    # Reserve in a background thread so creation is async (parity: the
    # reference returns immediately; `ready()` awaits placement).
    t = threading.Thread(target=_reserve, args=(rt, pg), daemon=True)
    t.start()
    rt.placement_groups = getattr(rt, "placement_groups", {})
    rt.placement_groups[pg.id] = pg
    return pg


def _live_placement_groups() -> List[PlacementGroup]:
    """All registered PGs of the current runtime (state API)."""
    rt = global_runtime()
    return list(getattr(rt, "placement_groups", {}).values())


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = global_runtime()
    with pg._state_lock:
        pg._removed = True
        for i, node_id in enumerate(pg._bundle_nodes):
            if node_id is not None:
                rt.scheduler.release(node_id,
                                     ResourceSet(pg.bundle_specs[i]))
                pg._bundle_nodes[i] = None
    getattr(rt, "placement_groups", {}).pop(pg.id, None)


def _reserve(rt, pg: PlacementGroup) -> None:
    deadline = time.monotonic() + config.gang_schedule_timeout_s
    while time.monotonic() < deadline:
        if pg._removed:
            return
        if _try_reserve_all(rt, pg):
            pg._ready.set()
            return
        time.sleep(0.02)
    pg._failed = "timed out acquiring bundles"
    pg._ready.set()


def repair_for_dead_node(rt, node_id: str) -> None:
    """Re-place bundles lost to a dead node onto survivors (reference:
    gcs_placement_group_manager.h — the GCS reschedules a PG's bundles
    when their node dies; tasks targeting the bundle stay queued until
    the new placement commits). Without this, an actor submitted into a
    bundle whose node died waits forever."""
    for pg in list(getattr(rt, "placement_groups", {}).values()):
        with pg._state_lock:
            lost = [i for i, n in enumerate(pg._bundle_nodes)
                    if n == node_id]
            if not lost:
                continue
            for i in lost:
                pg._bundle_nodes[i] = None
            # _bundle_available is deliberately NOT reset: in-flight
            # tasks charged into the lost bundle release their charge
            # back through release_task when their dispatch observes
            # the death — resetting here would double-credit and
            # oversubscribe the replacement node. _committed stays
            # True so surviving bundles keep dispatching.
        threading.Thread(
            target=_re_reserve, args=(rt, pg, lost), daemon=True,
            name=f"pg-repair-{pg.id[:6]}").start()


def _re_reserve(rt, pg: PlacementGroup, indices: List[int]) -> None:
    deadline = time.monotonic() + config.gang_schedule_timeout_s
    while time.monotonic() < deadline:
        if pg._removed:
            return
        with pg._repair_lock:
            ok = _try_reserve_indices(rt, pg, indices)
        if ok:
            pg._ready.set()
            # Queued tasks targeting the repaired bundles re-evaluate.
            rt.scheduler._pump()
            return
        time.sleep(0.05)
    pg._failed = f"could not re-place bundles {indices} after node death"
    pg._ready.set()


def _candidates(nodes: List[NodeState], strategy: str,
                used_nodes: set, anchor: Optional[str]
                ) -> List[NodeState]:
    if strategy == "STRICT_PACK":
        return ([n for n in nodes if n.node_id == anchor]
                if anchor else nodes)
    if strategy == "STRICT_SPREAD":
        return [n for n in nodes if n.node_id not in used_nodes]
    if strategy == "SPREAD":
        # Soft spread: fresh nodes first, but fall back to reusing a
        # node — "fresh or nodes" would pin the bundle to a fresh node
        # that can never fit it (e.g. the unschedulable driver head)
        # while a survivor has room.
        fresh = [n for n in nodes if n.node_id not in used_nodes]
        return fresh + [n for n in nodes if n.node_id in used_nodes]
    # PACK: prefer already-used nodes.
    return ([n for n in nodes if n.node_id in used_nodes] +
            [n for n in nodes if n.node_id not in used_nodes])


def _try_reserve_indices(rt, pg: PlacementGroup,
                         indices: List[int]) -> bool:
    """Phase-1 reserve for a SUBSET of bundles (repair path), honoring
    the strategy against the surviving bundles' placements."""
    nodes = [n for n in rt.scheduler.nodes()
             if n.alive and getattr(n, "schedulable", True)]
    placed: List[tuple] = []
    used_nodes = {n for n in pg._bundle_nodes if n is not None}
    anchor = next((n for n in pg._bundle_nodes if n is not None), None)
    chosen: Dict[int, NodeState] = {}
    for i in indices:
        rs = ResourceSet(pg.bundle_specs[i])
        ok = False
        for node in _candidates(nodes, pg.strategy, used_nodes, anchor):
            with rt.scheduler._lock:
                if rs.fits(node.available):
                    node.charge(rs)
                    ok = True
            if ok:
                placed.append((node, rs))
                chosen[i] = node
                used_nodes.add(node.node_id)
                if anchor is None:
                    anchor = node.node_id
                break
        if not ok:
            for node, rs2 in placed:
                rt.scheduler.release(node.node_id, rs2)
            return False
    with pg._state_lock:
        if pg._removed or any(
                not node.alive for node in chosen.values()):
            for node, rs2 in placed:
                rt.scheduler.release(node.node_id, rs2)
            return False
        for i, node in chosen.items():
            pg._bundle_nodes[i] = node.node_id
        pg._committed = True
    return True


def _try_reserve_all(rt, pg: PlacementGroup) -> bool:
    """Phase 1: tentatively subtract every bundle; rollback on any failure.

    The scheduler lock is per-operation, so this loop is the 'prepare' and
    a full rollback is the abort — single-process equivalent of the
    reference's PrepareBundleResources/CommitBundleResources 2PC.
    """
    nodes = [n for n in rt.scheduler.nodes()
             if n.alive and getattr(n, "schedulable", True)]
    placed: List[tuple] = []

    def rollback():
        for node, rs in placed:
            rt.scheduler.release(node.node_id, rs)

    chosen: List[Optional[NodeState]] = [None] * pg.bundle_count
    used_nodes: set = set()
    for i, spec in enumerate(pg.bundle_specs):
        rs = ResourceSet(spec)
        anchor = chosen[0].node_id if (i > 0 and chosen[0]) else None
        cands = _candidates(nodes, pg.strategy, used_nodes, anchor)
        ok = False
        for node in cands:
            if node is None:
                continue
            try:
                # Atomic per-node reserve through the scheduler lock.
                with rt.scheduler._lock:
                    if rs.fits(node.available):
                        # charge() (not a bare subtract) so heartbeat
                        # load reports account for this reservation.
                        node.charge(rs)
                        ok = True
                    else:
                        continue
            except ValueError:
                continue
            placed.append((node, rs))
            chosen[i] = node
            used_nodes.add(node.node_id)
            break
        if not ok:
            rollback()
            return False
    # Phase 2: commit — record bundle→node mapping and open the bundles
    # for task charging. Atomic vs removal: committing into a PG that
    # was removed mid-reserve would leak the charges forever. A node
    # that died between the snapshot and now must also abort: a bundle
    # committed onto a removed node never dispatches and — the death
    # event having already fired — would never be repaired either.
    with pg._state_lock:
        if pg._removed or any(
                node is None or not node.alive for node in chosen):
            rollback()
            return False
        for i, node in enumerate(chosen):
            pg._bundle_nodes[i] = node.node_id
        pg._committed = True
    return True
