"""pip runtime-env plugin — offline dependency isolation.

Capability-equivalent of the reference's pip plugin (reference:
python/ray/_private/runtime_env/pip.py — a per-env virtualenv built by
the runtime-env agent, URI-cached by content hash). Adapted to a
no-network TPU image:

- wheels come from a LOCAL wheelhouse (``find_links`` in the env spec,
  or the ``RAY_TPU_WHEELHOUSE`` env var); ``pip install --no-index``
  never touches the network, so a missing wheel is an immediate,
  clearly-attributed error instead of a hang.
- the env is materialized with ``pip install --target`` into a
  content-addressed cache dir and PREPENDED to ``sys.path`` for the
  task, rather than swapping interpreters: the base image is itself a
  venv, so a nested venv would lose jax/numpy (``--system-site-packages``
  does not chain), and the worker must keep the TPU stack. Same
  isolation semantics as the reference's env activation for pure-Python
  and same-interpreter binary wheels.
- one build per (packages, find_links) content hash per host, guarded
  by an flock; concurrent tasks wait for the winner's ``.ready`` marker
  (the reference's URI cache + per-env lock, uri_cache.py).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

WHEELHOUSE_ENV = "RAY_TPU_WHEELHOUSE"


def normalize_pip(spec: Any) -> Dict[str, Any]:
    """Accept ``"pip": [pkgs]`` or ``{"packages": [...], "find_links":
    path}``; returns the canonical dict or raises."""
    if isinstance(spec, (list, tuple)):
        spec = {"packages": list(spec)}
    if not isinstance(spec, dict):
        raise ValueError(
            "runtime_env['pip'] must be a list of requirements or a "
            "dict {'packages': [...], 'find_links': path}")
    pkgs = spec.get("packages")
    if (not isinstance(pkgs, (list, tuple)) or not pkgs
            or not all(isinstance(p, str) for p in pkgs)):
        raise ValueError(
            "runtime_env['pip']['packages'] must be a non-empty "
            "list of requirement strings")
    find_links = spec.get("find_links") or os.environ.get(WHEELHOUSE_ENV)
    if not find_links:
        raise ValueError(
            "runtime_env['pip'] needs a local wheelhouse: set "
            "'find_links' in the spec or the RAY_TPU_WHEELHOUSE env "
            "var (this image has no network for an index)")
    unknown = set(spec) - {"packages", "find_links"}
    if unknown:
        raise ValueError(
            f"unsupported runtime_env['pip'] keys {sorted(unknown)}")
    return {"packages": sorted(pkgs), "find_links": str(find_links)}


def env_hash(spec: Dict[str, Any]) -> str:
    h = hashlib.sha256()
    for p in spec["packages"]:
        h.update(p.encode())
        h.update(b"\0")
    h.update(spec["find_links"].encode())
    return h.hexdigest()[:16]


def cache_base() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "pip_cache")


def materialize_pip(spec: Dict[str, Any],
                    base_dir: Optional[str] = None) -> str:
    """Build (or reuse) the env dir for `spec`; returns the path to
    prepend to sys.path. Raises RuntimeError with pip's output when a
    wheel is missing from the wheelhouse — the documented offline
    failure mode."""
    import fcntl

    base = base_dir or cache_base()
    os.makedirs(base, exist_ok=True)
    dest = os.path.join(base, env_hash(spec))
    ready = os.path.join(dest, ".ready")
    if os.path.exists(ready):
        return dest
    with open(dest + ".lock", "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        if os.path.exists(ready):  # built while we waited
            return dest
        if os.path.exists(dest):
            shutil.rmtree(dest, ignore_errors=True)  # half-built
        cmd = [sys.executable, "-m", "pip", "install", "--quiet",
               "--no-index", "--find-links", spec["find_links"],
               "--target", dest] + list(spec["packages"])
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=600)
        if proc.returncode != 0:
            shutil.rmtree(dest, ignore_errors=True)
            raise RuntimeError(
                f"pip runtime_env build failed (offline install from "
                f"wheelhouse {spec['find_links']!r}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        with open(ready, "w") as f:
            f.write("\n".join(spec["packages"]) + "\n")
    return dest


def clear_cache(base_dir: Optional[str] = None) -> None:
    shutil.rmtree(base_dir or cache_base(), ignore_errors=True)
