"""Per-task/actor runtime environments.

Capability-equivalent of the reference's runtime_env plugin vocabulary
(reference: python/ray/runtime_env/, _private/runtime_env/ — plugins
pip/conda/working_dir/py_modules/env_vars; applied by the per-node agent
before a lease is granted). Here the supported subset — env_vars,
working_dir, py_modules, and an OFFLINE pip plugin (local-wheelhouse
installs, runtime_env_pip.py) — is applied around each user-code
invocation and fully restored afterwards, in whichever process executes
the task (driver-embedded node or spawned worker). Caveat shared with
the reference's worker reuse: modules already imported from an env
linger in sys.modules after the path is removed.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Dict, Optional

VALID_KEYS = frozenset({"env_vars", "working_dir", "py_modules", "pip",
                        "conda", "container"})


def validate(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not renv:
        return None
    if not isinstance(renv, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(renv)}")
    unknown = set(renv) - VALID_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(VALID_KEYS)}")
    ev = renv.get("env_vars")
    if ev is not None and (not isinstance(ev, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in ev.items())):
        raise ValueError("runtime_env['env_vars'] must be a dict[str, str]")
    wd = renv.get("working_dir")
    if wd is not None and not isinstance(wd, (str, os.PathLike)):
        raise ValueError("runtime_env['working_dir'] must be a path")
    pm = renv.get("py_modules")
    if pm is not None and (not isinstance(pm, (list, tuple)) or not all(
            isinstance(p, (str, os.PathLike)) for p in pm)):
        raise ValueError("runtime_env['py_modules'] must be a list of paths")
    if renv.get("pip") is not None and renv.get("conda") is not None:
        raise ValueError(
            "runtime_env cannot combine 'pip' and 'conda' "
            "(reference semantics: pip installs ride inside the "
            "conda spec's dependencies instead)")
    if renv.get("pip") is not None:
        from .runtime_env_pip import normalize_pip

        renv = dict(renv)
        renv["pip"] = normalize_pip(renv["pip"])
    if renv.get("conda") is not None:
        from .runtime_env_isolation import normalize_conda

        renv = dict(renv)
        renv["conda"] = normalize_conda(renv["conda"])
    if renv.get("container") is not None:
        from .runtime_env_isolation import normalize_container

        renv = dict(renv)
        renv["container"] = normalize_container(renv["container"])
    return renv


@contextlib.contextmanager
def applied(renv: Optional[Dict[str, Any]]):
    """Apply `renv` for the duration of one task; restore on exit.

    env vars are set and restored, working_dir chdir'd, py_modules
    prepended to sys.path. Process-global by nature — concurrent tasks
    in the same process observe each other's env while active (the
    reference gives each runtime_env its own worker process; the
    spawned-worker path here does too).
    """
    if not renv:
        yield
        return
    if renv.get("container") is not None:
        # A container can only take effect by launching the worker
        # process inside the image (reference: container.py); it can
        # never be applied to an already-running worker. The command
        # wrap exists (runtime_env_isolation.wrap_cmd_container) but is
        # not wired into this execution plane — refuse rather than
        # silently ignore.
        from .runtime_env_isolation import RuntimeEnvUnsupportedError

        raise RuntimeEnvUnsupportedError(
            "runtime_env['container'] needs the worker launched inside "
            "the image (podman/docker), which this execution plane does "
            "not do. Ship code with working_dir/py_modules and "
            "dependencies via the offline pip plugin "
            "(runtime_env={'pip': [...]}).")
    conda_env_dir: Optional[str] = None
    if renv.get("conda") is not None:
        # With a conda binary on the host, materialize the env and apply
        # its site-packages in-process (same interpreter-stays caveat as
        # the pip plugin). Without one — this image — refuse with the
        # supported alternative.
        from .runtime_env_isolation import (
            RuntimeEnvUnsupportedError,
            conda_binary,
            conda_site_packages,
            materialize_conda,
        )

        spec = renv["conda"]
        if conda_binary() is None:
            raise RuntimeEnvUnsupportedError(
                "runtime_env['conda'] needs a conda binary on the host "
                "and none was found. Use the offline pip plugin for "
                "dependency isolation (runtime_env={'pip': [...]}, local "
                "wheelhouse via RAY_TPU_WHEELHOUSE) and "
                "working_dir/py_modules for code shipping.")
        if spec.get("kind") == "name":
            raise RuntimeEnvUnsupportedError(
                "named conda envs need the worker launched via `conda "
                "run -n` (spawn-level, runtime_env_isolation."
                "wrap_cmd_conda); pass a dependency list or environment "
                "dict/yaml instead to apply the env in place.")
        prefix = materialize_conda(spec)
        conda_env_dir = conda_site_packages(prefix)
        if conda_env_dir is None:
            raise RuntimeEnvUnsupportedError(
                f"conda env at {prefix} has no site-packages (no python "
                "in its dependencies?)")
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = None
    added_paths = []
    set_env: Dict[str, str] = {}
    try:
        for k, v in (renv.get("env_vars") or {}).items():
            saved_env[str(k)] = os.environ.get(str(k))
            set_env[str(k)] = str(v)
            os.environ[str(k)] = str(v)
        wd = renv.get("working_dir")
        if wd:
            saved_cwd = os.getcwd()
            os.chdir(wd)
        for p in (renv.get("py_modules") or []):
            p = str(p)
            if p not in sys.path:
                sys.path.insert(0, p)
                added_paths.append(p)
        if renv.get("pip"):
            # Materialized once per content hash per host (flock +
            # .ready marker); later tasks reuse the cached dir.
            from .runtime_env_pip import materialize_pip

            env_dir = materialize_pip(renv["pip"])
            if env_dir not in sys.path:
                sys.path.insert(0, env_dir)
                added_paths.append(env_dir)
        if conda_env_dir is not None and conda_env_dir not in sys.path:
            sys.path.insert(0, conda_env_dir)
            added_paths.append(conda_env_dir)
        yield
    finally:
        for k, old in saved_env.items():
            # CAS-style restore: only undo values this context set and
            # that nobody overwrote since — overlapping contexts ending
            # out of order must not reinstate each other's values.
            if os.environ.get(k) != set_env.get(k):
                continue
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if saved_cwd is not None:
            try:
                still_ours = os.path.samefile(
                    os.getcwd(), renv.get("working_dir"))
            except OSError:
                still_ours = False
            if still_ours:
                os.chdir(saved_cwd)
        for p in added_paths:
            with contextlib.suppress(ValueError):
                sys.path.remove(p)
