"""Actor execution state (local thread actors + process actors).

Split out of core/runtime.py (VERDICT r3 #9): the per-actor mailbox /
restart / redelivery machinery (reference:
direct_actor_transport.{h,cc}, actor_scheduling_queue.h,
gcs_actor_manager.h restart FSM). Every name is re-exported from
runtime for compatibility.
"""

from __future__ import annotations

import contextlib
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .._private.config import config
from ..observability import get_recorder, record_task_metrics
from ..util import tracing as _tracing
from .exceptions import (
    ActorDiedError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
)
from .ids import ActorID, ObjectID, TaskID
from .object_ref import ObjectRef
from .runtime_env import applied as _renv_applied
from .runtime_support import _ctx
from .task import TaskSpec

logger = logging.getLogger("ray_tpu")

# ---------------------------------------------------------------------------
# Actors
# ---------------------------------------------------------------------------

class _ActorExit(BaseException):
    pass


class ActorState:
    """A live actor: dedicated mailbox + executor thread(s).

    Mirrors the reference's direct actor transport semantics
    (direct_actor_task_submitter.h): per-caller ordered delivery (here:
    one global FIFO mailbox), max_concurrency via a pool, async actors via
    an embedded event loop. Method exceptions are stored as error objects;
    the actor stays alive (parity with the reference)."""

    def __init__(self, rt: "Runtime", actor_id: ActorID, cls: type,
                 args, kwargs, *, node: NodeState, name: str,
                 max_concurrency: int, max_restarts: int,
                 resources: ResourceSet,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 max_task_retries: int = 0,
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 detached: bool = False):
        self.rt = rt
        self.actor_id = actor_id
        # Creation stamp: the outstanding-resource ledger ages actor
        # rows from it (a PENDING_CREATION stuck past the leak
        # threshold becomes a suspect; ALIVE is leak-exempt).
        self.created_at = time.time()
        # lifetime="detached": survives this driver (reference:
        # gcs_actor_manager.h detached actors); on the daemon plane the
        # hosting worker outlives the creator's connection.
        self.detached = detached
        self.cls = cls
        self.init_args = args
        self.init_kwargs = kwargs
        self.runtime_env = runtime_env
        self.node = node
        self.name = name
        self.max_concurrency = max(1, max_concurrency)
        self.max_restarts = max_restarts
        # Method calls interrupted by a restartable actor death are
        # re-delivered after the restart up to this many times
        # (reference: max_task_retries).
        self.max_task_retries = max_task_retries
        self.restarts = 0
        self.resources = resources
        self.mailbox: "queue.Queue" = queue.Queue(maxsize=config.actor_queue_max)
        # Crash-interrupted calls re-enter HERE, consumed before the
        # mailbox — redelivery must not jump behind later submissions
        # (ordered-delivery contract) and must never block (unbounded).
        self.redeliver_q: "queue.Queue" = queue.Queue()
        # Named concurrency groups: each group gets its own mailbox +
        # thread pool, so slow methods in one group don't head-of-line
        # block another (reference: concurrency_group_manager.h).
        # Thread-based actors only — a proc actor's dedicated worker is
        # one process and serializes regardless (see ProcActorState).
        self.concurrency_groups = dict(concurrency_groups or {})
        # Bounded like the main mailbox: group routing must not bypass
        # actor backpressure.
        self.group_mailboxes: Dict[str, "queue.Queue"] = {
            g: queue.Queue(maxsize=config.actor_queue_max)
            for g in self.concurrency_groups}
        self.dead = threading.Event()
        self.ready = threading.Event()
        # @method(...) per-method defaults, resolvable even when the
        # class body is not importable locally (cross-driver proxies
        # receive these from the control plane's actor table).
        self.method_defaults: Dict[str, Dict[str, Any]] = {
            m: dict(getattr(getattr(cls, m), "_ray_method_opts"))
            for m in dir(cls)
            if not m.startswith("__")
            and hasattr(getattr(cls, m, None), "_ray_method_opts")
        }
        self.death_cause: Optional[BaseException] = None
        self.instance = None
        self._death_lock = threading.Lock()
        self._death_done = False
        self.generation = 0  # bumped on restart; stale threads no-op in _die
        self._restartable_kill = False
        self._is_async = any(
            _is_coro_fn(getattr(cls, m, None)) for m in dir(cls)
            if not m.startswith("__")
        )
        self._threads: List[threading.Thread] = []
        self._start_threads()

    def _start_threads(self):
        gen = self.generation
        if self._is_async:
            t = threading.Thread(
                target=self._async_main, args=(gen,),
                name=f"actor-{self.name}", daemon=True)
            t.start()
            self._threads = [t]
        else:
            # First thread constructs the instance; extras join after ready.
            t = threading.Thread(
                target=self._sync_main, args=(True, gen),
                name=f"actor-{self.name}", daemon=True)
            t.start()
            self._threads = [t]
            for i in range(1, self.max_concurrency):
                t = threading.Thread(
                    target=self._sync_main, args=(False, gen),
                    name=f"actor-{self.name}-{i}", daemon=True)
                t.start()
                self._threads.append(t)
            for group, limit in self._group_pools().items():
                mbox = self.group_mailboxes[group]
                for i in range(limit):
                    t = threading.Thread(
                        target=self._sync_main, args=(False, gen, mbox),
                        name=f"actor-{self.name}-{group}-{i}",
                        daemon=True)
                    t.start()
                    self._threads.append(t)

    # -- lifecycle --------------------------------------------------------
    def _construct(self, gen: int) -> bool:
        try:
            with _renv_applied(self.runtime_env):
                self.instance = self.cls(*self.init_args,
                                         **self.init_kwargs)
            self.ready.set()
            return True
        except BaseException as e:  # noqa: BLE001
            self.death_cause = TaskError(self.cls.__name__ + ".__init__", e)
            self._die(gen)
            return False

    def _die(self, gen: int):
        """Called by every worker thread on loop exit. Only the first thread
        of the *current* generation performs death bookkeeping (resource
        release must happen exactly once); restart bumps the generation so
        stale threads become no-ops
        (reference restart semantics: gcs_actor_manager.h:513
        GcsActorManager::ReconstructActor)."""
        with self._death_lock:
            if gen != self.generation or self._death_done:
                return
            if self._restartable_kill and self.restarts < self.max_restarts:
                self.restarts += 1
                logger.info("Restarting actor %s (%d/%d)",
                            self.name, self.restarts, self.max_restarts)
                self._restartable_kill = False
                self.death_cause = None
                self.instance = None  # raylint: disable=unguarded-handle-teardown -- Python object, not a native handle; stale-generation worker threads no-op on the generation check before touching instance
                self.generation += 1
                self.dead.clear()
                self.ready.clear()
                self._start_threads()
                return
            self._death_done = True
        self.dead.set()
        self.ready.set()
        # Flight-recorder bookkeeping OUTSIDE the death lock (auto_dump
        # does file IO). Deliberate exits (kill / exit_actor) are
        # recorded but don't trigger a crash dump.
        cause = self.death_cause
        deliberate = isinstance(cause, ActorDiedError) and any(
            s in str(cause) for s in ("Killed via", "exit_actor"))
        rec = get_recorder()
        rec.record("scheduler", "actor_died",
                   actor=self.name or self.actor_id.hex(),
                   cause=repr(cause)[:200] if cause else "shutdown",
                   restarts=self.restarts)
        if cause is not None and not deliberate:
            rec.auto_dump("actor_died")
        # Drain all mailboxes (+ redelivery queue) with death errors.
        drains = [self.redeliver_q, self.mailbox,
                  *self.group_mailboxes.values()]
        def _next_spec():
            for q_ in drains:
                try:
                    return q_.get_nowait()
                except queue.Empty:
                    continue
            return StopIteration
        while True:
            spec = _next_spec()
            if spec is StopIteration:
                break
            if spec is not None:
                try:
                    self.rt._store_error(
                        spec,
                        self.death_cause
                        or ActorDiedError(self.actor_id.hex()),
                    )
                    self.rt._task_finished(spec)
                except BaseException as e:  # noqa: BLE001 - one bad spec
                    # must not strand the rest of the drained mailbox
                    self.rt._fail_spec_internal(spec, e)
        self.rt._on_actor_dead(self)

    def kill(self, *, no_restart: bool = True):
        self.death_cause = ActorDiedError(
            self.actor_id.hex(), "Killed via ray_tpu.kill().")
        self._restartable_kill = not no_restart
        self.dead.set()
        try:
            self.mailbox.put_nowait(None)  # wake the loop
        except queue.Full:
            pass

    def _group_pools(self) -> Dict[str, int]:
        """Groups that get dedicated threads (ProcActorState: none —
        its dedicated worker process is a single pipeline; async actors:
        none — the event loop is already concurrent and only the main
        mailbox is drained)."""
        return {} if self._is_async else self.concurrency_groups

    # Mailbox wake marker: enqueued when something lands in
    # redeliver_q so an IDLE mailbox notices immediately without the
    # loop polling. A short get-timeout looked harmless but at the 10k-
    # actor scale point 10 wakeups/s/thread saturates the host with
    # context switches before any work runs.
    _WAKE = object()

    # -- execution --------------------------------------------------------
    def _sync_main(self, constructs: bool, gen: int, mbox=None):
        _ctx.actor_id = self.actor_id
        _ctx.node_id = self.node.node_id
        if constructs:
            if not self._construct(gen):
                return
        else:
            self.ready.wait()
        own_mbox = mbox if mbox is not None else self.mailbox
        main_loop = mbox is None
        while not self.dead.is_set() and gen == self.generation:
            try:
                # Redelivered calls are drained by the main pool only.
                if not main_loop:
                    raise queue.Empty
                spec = self.redeliver_q.get_nowait()
            except queue.Empty:
                try:
                    spec = own_mbox.get(timeout=5.0)
                except queue.Empty:
                    continue
            if spec is ActorState._WAKE:
                continue
            if spec is None and not self.dead.is_set():
                # Stale kill sentinel: the previous generation exited on
                # the dead flag without consuming it and the restart
                # already cleared dead — honoring it would kill the
                # fresh generation with no cause.
                continue
            if spec is None or self.dead.is_set():
                # A real spec popped in the same race as the kill must
                # reach the death drain — breaking here would drop it
                # with its returns forever pending.
                if spec is not None:
                    self.redeliver_q.put(spec)
                    # _death_done is set BEFORE the drain runs, so if
                    # it is visible the drain may already have passed
                    # redeliver_q — drain any leftovers here (pop
                    # ownership is exclusive, so this never double-
                    # stores against the real drain).
                    with self._death_lock:
                        death_done = self._death_done
                    if death_done:
                        while True:
                            try:
                                s2 = self.redeliver_q.get_nowait()
                            except queue.Empty:
                                break
                            try:
                                self.rt._store_error(
                                    s2, self.death_cause
                                    or ActorDiedError(
                                        self.actor_id.hex()))
                                self.rt._task_finished(s2)
                            except BaseException as e:  # noqa: BLE001
                                self.rt._fail_spec_internal(s2, e)
                break
            try:
                self._run_method(spec)
            except BaseException as e:  # noqa: BLE001 - an internal bug
                # must fail THIS call, not kill the mailbox thread and
                # strand every queued call (VERDICT r4 weak #2)
                self.rt._fail_spec_internal(spec, e)
        self._die(gen)

    def _async_main(self, gen: int):
        import asyncio
        _ctx.actor_id = self.actor_id
        _ctx.node_id = self.node.node_id
        if not self._construct(gen):
            return
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        sem = asyncio.Semaphore(self.max_concurrency)

        async def runner():
            while not self.dead.is_set():
                try:
                    spec = await loop.run_in_executor(
                        None, lambda: self.mailbox.get(timeout=5.0))
                except queue.Empty:
                    continue
                if spec is ActorState._WAKE:
                    continue
                if spec is None:
                    if not self.dead.is_set():
                        continue  # stale kill sentinel (see _sync_main)
                    break

                async def run_one(s=spec):
                    async with sem:
                        try:
                            await self._run_method_async(s)
                        except BaseException as e:  # noqa: BLE001
                            self.rt._fail_spec_internal(s, e)

                loop.create_task(run_one())
            # let in-flight tasks finish
            pending = [t for t in asyncio.all_tasks(loop)
                       if t is not asyncio.current_task()]
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)

        try:
            loop.run_until_complete(runner())
        finally:
            loop.close()
            self._die(gen)

    def _bind_method(self, spec: TaskSpec):
        if spec.method_name == "__ray_tpu_apply__":
            # Injected execution: first arg is a callable taking the
            # actor instance (compiled-DAG loops use this to pin a
            # driver-provided loop onto the actor; reference:
            # compiled_dag_node.py do_exec_compiled_task).
            return lambda fn, *a, **kw: fn(self.instance, *a, **kw)
        method = getattr(self.instance, spec.method_name)
        return method

    def _enter_method_trace(self, spec: TaskSpec) -> contextlib.ExitStack:
        """Lifecycle stamps + trace re-entry shared by the sync/async/
        proc method runners. Mailbox pickup = scheduled; now = running."""
        spec.timing.setdefault("scheduled", time.time())
        spec.timing["running"] = time.time()
        stack = contextlib.ExitStack()
        if spec.trace_id:
            stack.enter_context(_tracing.trace_context(
                spec.trace_id, spec.parent_span_id))
            stack.enter_context(_tracing.span(
                f"actor:{spec.display_name()}", "actor_execute",
                task_id=spec.task_id.hex(),
                actor_id=self.actor_id.hex()))
        return stack

    def _finish_method(self, spec: TaskSpec, t0: float,
                       failed: bool) -> None:
        spec.timing["finished"] = time.time()
        self.rt._task_finished(spec)
        record_task_metrics(spec.timing,
                            "FAILED" if failed else "FINISHED")
        self.rt.events.record(
            spec.display_name(), t0, time.monotonic(),
            self.node.node_id, spec.task_id.hex(),
            timing=spec.timing, trace_id=spec.trace_id,
            deps=spec.dep_ids(), returns=spec.return_hexes())

    def _run_method(self, spec: TaskSpec):
        _ctx.task_id = spec.task_id
        t0 = time.monotonic()
        failed = False
        trace_cm = self._enter_method_trace(spec)
        try:
            method = self._bind_method(spec)
            args, kwargs = self.rt._materialize_args(spec)
            with _renv_applied(self.runtime_env):
                result = method(*args, **kwargs)
            self.rt._store_results(spec, result, t0)
        except _ActorExit:
            self.rt._store_results(spec, None, t0)
            self.death_cause = ActorDiedError(
                self.actor_id.hex(), "exit_actor() was called.")
            self.dead.set()
        except BaseException as e:  # noqa: BLE001
            failed = True
            self.rt._store_error(spec, _wrap(spec, e), t0)
        finally:
            trace_cm.close()
            _ctx.task_id = None
            self._finish_method(spec, t0, failed)

    async def _run_method_async(self, spec: TaskSpec):
        _ctx.task_id = spec.task_id
        t0 = time.monotonic()
        failed = False
        trace_cm = self._enter_method_trace(spec)
        try:
            method = self._bind_method(spec)
            args, kwargs = self.rt._materialize_args(spec)
            with _renv_applied(self.runtime_env):
                result = method(*args, **kwargs)
                if hasattr(result, "__await__"):
                    result = await result
            self.rt._store_results(spec, result, t0)
        except _ActorExit:
            self.rt._store_results(spec, None, t0)
            self.death_cause = ActorDiedError(
                self.actor_id.hex(), "exit_actor() was called.")
            self.dead.set()
        except BaseException as e:  # noqa: BLE001
            failed = True
            self.rt._store_error(spec, _wrap(spec, e), t0)
        finally:
            trace_cm.close()
            _ctx.task_id = None
            self._finish_method(spec, t0, failed)


class ProcActorState(ActorState):
    """An actor hosted by a dedicated worker PROCESS (worker_proc.py).

    Reuses ActorState's mailbox/restart/death machinery; only
    construction and method execution are overridden to round-trip
    through the worker. A worker crash is an actor death that follows
    the normal max_restarts policy — the restart's _construct leases a
    fresh worker and re-runs __init__ (reference:
    gcs_actor_manager.h:513 ReconstructActor after worker failure)."""

    def __init__(self, *args, **kwargs):
        self._worker = None
        # One worker socket == one in-flight call; concurrency groups
        # stay an in-process-actor feature.
        kwargs["max_concurrency"] = 1
        super().__init__(*args, **kwargs)

    @property
    def _pool(self):
        return self.node.pool

    def _start_threads(self):
        # Always the sync mailbox loop: coroutine methods are awaited
        # worker-side (asyncio.run in worker_main).
        self._is_async = False
        super()._start_threads()

    def _construct(self, gen: int) -> bool:
        import cloudpickle

        from .worker_proc import WorkerCrashedError

        if self._worker is not None:  # restart: retire the old worker
            self._pool.retire(self._worker)
            self._worker = None
        w = None
        try:
            # A dedicated worker per actor (reference: the raylet spawns
            # a fresh worker process for every actor) — actors never
            # drain the task pool.
            w = self._pool.spawn_dedicated()
            create_msg = {
                "type": "actor_create",
                "task_id": None,
                "actor_id": self.actor_id.binary(),
                "cls": cloudpickle.dumps(self.cls),
                "args": tuple(self.rt._pack_arg(a) for a in self.init_args),
                "kwargs": {k: self.rt._pack_arg(v)
                           for k, v in self.init_kwargs.items()},
            }
            if self.runtime_env:
                create_msg["runtime_env"] = self.runtime_env
            reply = w.run_task(create_msg)
            for ev in reply.get("spans") or ():
                self.rt.events.record_raw(ev)
            if reply.get("error") is not None:
                raise self.rt._unpack_error(reply["error"])
            self._worker = w
            self.instance = w  # marker: lives remotely
            self.ready.set()
            return True
        except BaseException as e:  # noqa: BLE001
            if w is not None:
                self._pool.retire(w)
            if isinstance(e, WorkerCrashedError):
                self._restartable_kill = True  # worker death is restartable
            self.death_cause = TaskError(self.cls.__name__ + ".__init__", e)
            self._die(gen)
            return False

    def _group_pools(self) -> Dict[str, int]:
        # The dedicated worker is ONE process: group threads would race
        # on its socket for no parallelism — groups collapse into the
        # ordered mailbox (routing in submit_actor_task).
        return {}

    def _run_method(self, spec: TaskSpec):
        from .worker_proc import WorkerCrashedError

        spec.redelivered = False  # fresh delivery (incl. retry passes)
        _ctx.task_id = spec.task_id
        t0 = time.monotonic()
        failed = False
        streaming = spec.num_returns in ("streaming", "dynamic")
        gst = self.rt._generators.get(spec.task_id) if streaming else None
        trace_cm = self._enter_method_trace(spec)
        try:
            msg = {
                "type": "actor_call",
                "task_id": spec.task_id,
                "actor_id": self.actor_id.binary(),
                "method": spec.method_name,
                "args": tuple(self.rt._pack_arg(a) for a in spec.args),
                "kwargs": {k: self.rt._pack_arg(v)
                           for k, v in spec.kwargs.items()},
                "num_returns": 0 if streaming else spec.num_returns,
                "return_ids": [oid.binary() for oid in spec.return_ids],
                "streaming": streaming,
            }
            if spec.trace_id:
                msg["trace_id"] = spec.trace_id
                msg["parent_span_id"] = (_tracing.current_span_id()
                                         or spec.parent_span_id)
            if streaming and gst is not None:
                msg["backpressure"] = \
                    config.generator_backpressure_max_items
            if self.runtime_env:
                msg["runtime_env"] = self.runtime_env

            def on_stream(item):
                oid = ObjectID.for_return(spec.task_id, item["index"])
                with self.rt.lineage_lock:
                    self.rt.lineage[oid] = spec
                self.rt._store_packed(oid, item["payload"])
                if gst is not None:
                    ref = self.rt.register_ref(ObjectRef(oid))
                    with gst.cv:
                        gst.refs.append(ref)
                        gst.cv.notify_all()

            if gst is not None:
                with gst.cv:
                    gst.ack_cb = self._worker.send_ack
            try:
                reply = self._worker.run_task(
                    msg, on_stream=on_stream if streaming else None)
            finally:
                if gst is not None:
                    with gst.cv:
                        gst.ack_cb = None
            # Merge worker-side spans BEFORE the error check — failed
            # calls keep their trace.
            for ev in reply.get("spans") or ():
                self.rt.events.record_raw(ev)
            if reply.get("error") is not None:
                err = self.rt._unpack_error(reply["error"])
                if isinstance(err, _ActorExit):
                    self.rt._store_results(spec, None, t0)
                    self.death_cause = ActorDiedError(
                        self.actor_id.hex(), "exit_actor() was called.")
                    self.dead.set()
                    return
                raise err
            if streaming and gst is not None:
                with gst.cv:
                    gst.done = True
                    gst.cv.notify_all()
                self.rt._generators.pop(spec.task_id, None)
            else:
                for oid, packed in zip(spec.return_ids, reply["returns"]):
                    self.rt._store_packed(oid, packed)
        except WorkerCrashedError as e:
            left = spec.task_retries_left
            if left is None:
                left = self.max_task_retries
            will_restart = self.restarts < self.max_restarts
            get_recorder().record(
                "scheduler", "actor_worker_crashed",
                actor=self.name or self.actor_id.hex(),
                method=spec.method_name or "",
                will_restart=will_restart)
            self.death_cause = ActorDiedError(
                self.actor_id.hex(), f"worker process died: {e}")
            self._restartable_kill = True  # honor max_restarts
            # -1 = retry forever (reference max_task_retries semantics).
            # Streaming calls are NOT redelivered: their generator state
            # already holds delivered items and a rerun would duplicate
            # them for the consumer.
            if (left != 0) and will_restart and not streaming:
                # Re-deliver the interrupted call to the restarted
                # actor instead of erroring it. The task stays pending
                # (the finally must not pop it, or a concurrent get()
                # could lineage-resubmit it).
                spec.task_retries_left = left - 1 if left > 0 else left
                spec.redelivered = True
                self.redeliver_q.put(spec)
                with contextlib.suppress(queue.Full):
                    self.mailbox.put_nowait(ActorState._WAKE)
                self.dead.set()
                return
            failed = True
            self.rt._store_error(spec, _wrap(spec, e), t0)
            self.dead.set()
        except BaseException as e:  # noqa: BLE001
            failed = True
            self.rt._store_error(spec, _wrap(spec, e), t0)
        finally:
            trace_cm.close()
            _ctx.task_id = None
            if not spec.redelivered:
                self._finish_method(spec, t0, failed)

    def _die(self, gen: int):
        super()._die(gen)
        # Final death (not a restart): retire the dedicated worker.
        if self.dead.is_set() and self._worker is not None:
            w = self._worker
            self._worker = None  # raylint: disable=unguarded-handle-teardown -- lifecycle-ordered: _construct() runs before the worker loop that can reach _die(), and the null copies to a local first
            self._pool.retire(w)


def _is_coro_fn(f) -> bool:
    import inspect
    return f is not None and inspect.iscoroutinefunction(f)


def _wrap(spec: TaskSpec, e: BaseException) -> BaseException:
    if isinstance(e, (TaskError, ActorDiedError, TaskCancelledError,
                      ObjectLostError)):
        return e
    return TaskError(spec.display_name(), e)

