"""Exception taxonomy.

Capability-equivalent to the reference's exception surface
(reference: python/ray/exceptions.py): user-code failures wrapped with the
remote traceback, actor death, object loss, cancellation, and get timeout.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    Carries the remote traceback text; re-raised at every `get` on any of
    the task's return refs, and propagated through dependent tasks
    (error poisoning, as in the reference).
    """

    def __init__(self, function_name: str, cause: BaseException,
                 remote_tb: Optional[str] = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_tb = remote_tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"Task {function_name!r} failed: "
            f"{type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{self.remote_tb}"
        )

    def __reduce__(self):
        try:
            import pickle
            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:  # noqa: BLE001 — unpicklable user exception
            cause = RuntimeError(f"{type(self.cause).__name__}: {self.cause}")
        return (TaskError, (self.function_name, cause, self.remote_tb))


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    def __init__(self, actor_id_hex: str, reason: str = ""):
        self.actor_id_hex = actor_id_hex
        super().__init__(f"Actor {actor_id_hex} died. {reason}".strip())


class ActorUnavailableError(ActorError):
    pass


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str):
        self.object_id_hex = object_id_hex
        super().__init__(
            f"Object {object_id_hex} was lost and could not be reconstructed."
        )


class TaskCancelledError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class ObjectStoreFullError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass
